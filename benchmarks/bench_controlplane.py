"""Control-plane churn benchmark: reconcile-path throughput at scale.

The training-path headline (bench.py) is HBM-bound and exhausted; this
harness watches the OTHER hot path — the reconcile loop — at the fleet
shapes the pod-scale papers describe (one controller owning thousands
of pods across hundreds of gangs).

Shape: create N TPUJobs x M worker pods against the in-process Store
(API-server analog, no data plane), with a fake kubelet driving every
pod Pending -> Running -> Succeeded. The controller must observe the
churn, create pods/endpoints, roll up statuses, and converge every job
to Succeeded. Reported:

- convergence_seconds: first job create -> last job Succeeded
- jobs_per_sec: N / convergence_seconds (the headline; the acceptance
  target is >=5x over the pre-PR controller at 200 jobs x 16 pods)
- syncs + syncs_per_sec and exact p50/p99 reconcile latency (measured
  around sync_tpujob, not bucketized)
- deepcopies_per_sync: ApiObject.deepcopy calls / syncs — the
  per-sync allocation proxy (store snapshots + single-list syncs are
  exactly the levers that move it)

Prints exactly ONE JSON line (bench.py artifact discipline), with the
environment fingerprint satellite fields (jax version, platform,
config fingerprint) so round-over-round medians are auditable.

Usage:
    python benchmarks/bench_controlplane.py                  # 200x16
    python benchmarks/bench_controlplane.py --jobs 5 --workers 2  # smoke
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import platform as _platform
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu import testutil  # noqa: E402
from tf_operator_tpu.api.types import (  # noqa: E402
    ContainerStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodStatus,
)
from tf_operator_tpu.api import constants  # noqa: E402
from tf_operator_tpu.api.serde import ApiObject  # noqa: E402
from tf_operator_tpu.controller import conditions as cond  # noqa: E402
from tf_operator_tpu.controller.tpu_controller import (  # noqa: E402
    TPUJobController,
)
from tf_operator_tpu.runtime import store as store_mod  # noqa: E402
from tf_operator_tpu.runtime.store import Store  # noqa: E402

NAMESPACE = "bench"


class FakeKubelet(threading.Thread):
    """Drives pod phases like a node agent: every tick, Pending pods
    start Running and Running pods complete with exit 0. One phase per
    tick so the controller observes the full lifecycle churn.

    ``admitted``: optional (namespace, job_name) -> bool gate — the
    gang-gated data-plane analog for the tenant scenario: a Pending pod
    only starts once its SliceGroup is admitted (without it, pods of
    quota-held gangs would run anyway and the contention measurement
    would be fiction).

    ``min_run_seconds``: hold Running pods at least this long before
    completing them — the tenant scenario needs borrowers still
    RUNNING when the late tenant's nominal demand arrives, or there is
    nothing to reclaim."""

    def __init__(self, store: Store, tick: float = 0.01, admitted=None,
                 min_run_seconds: float = 0.0):
        super().__init__(name="fake-kubelet", daemon=True)
        self.store = store
        self.tick = tick
        self.admitted = admitted
        self.min_run_seconds = min_run_seconds
        self._run_since: Dict[Tuple[str, str], float] = {}
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            transitions = self.store.project(
                store_mod.PODS,
                lambda p: ((p.metadata.namespace, p.metadata.name,
                            p.status.phase,
                            p.metadata.labels.get(
                                constants.LABEL_JOB_NAME, ""))
                           if p.status.phase in (PodPhase.PENDING,
                                                 PodPhase.RUNNING)
                           else None),
                namespace=NAMESPACE)
            now = time.perf_counter()
            for ns, name, phase, job_name in transitions:
                patch = Pod(metadata=ObjectMeta(name=name, namespace=ns))
                if phase == PodPhase.PENDING:
                    if (self.admitted is not None
                            and not self.admitted(ns, job_name)):
                        continue  # gang gate: held until admission
                    self._run_since[(ns, name)] = now
                    patch.status = PodStatus(phase=PodPhase.RUNNING,
                                             start_time=testutil.now())
                elif (self.min_run_seconds
                        and now - self._run_since.get((ns, name), 0.0)
                        < self.min_run_seconds):
                    continue  # still inside its minimum runtime
                else:
                    patch.status = PodStatus(
                        phase=PodPhase.SUCCEEDED,
                        start_time=testutil.now(),
                        container_statuses=[ContainerStatus(
                            name=constants.DEFAULT_CONTAINER_NAME,
                            state="Terminated", exit_code=0)])
                try:
                    self.store.update_status(store_mod.PODS, patch)
                except (store_mod.NotFoundError, store_mod.ConflictError):
                    pass  # reaped or raced by the controller; benign
            self._stop.wait(self.tick)


class _SyncTimer:
    """Wraps sync_tpujob to count syncs and record exact durations
    (the metrics histogram is bucketized; p99 wants raw samples)."""

    def __init__(self, controller: TPUJobController):
        self._inner = controller.sync_tpujob
        self.durations: List[float] = []
        self._lock = threading.Lock()
        controller.sync_tpujob = self  # type: ignore[assignment]

    def __call__(self, key: str) -> None:
        t0 = time.perf_counter()
        try:
            self._inner(key)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.durations.append(dt)

    def snapshot(self) -> List[float]:
        with self._lock:
            return list(self.durations)


class _DeepcopyCounter:
    """Counts ApiObject.deepcopy calls — the dominant per-sync
    allocation source in the reconcile path."""

    def __init__(self):
        self.count = 0
        self._orig = ApiObject.deepcopy
        counter = self

        def counted(obj):
            counter.count += 1
            return counter._orig(obj)

        ApiObject.deepcopy = counted

    def stop(self) -> int:
        ApiObject.deepcopy = self._orig
        return self.count


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


def run_bench(jobs: int, workers: int, threadiness: int,
              timeout: float, kubelet_tick: float = 0.01) -> Dict:
    """Returns the artifact dict (not yet JSON-encoded). Raises
    TimeoutError if the fleet does not converge within ``timeout``."""
    store = Store()
    controller = TPUJobController(store, namespace=NAMESPACE)
    timer = _SyncTimer(controller)
    copies = _DeepcopyCounter()
    kubelet = FakeKubelet(store, tick=kubelet_tick)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers, name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            store.create(store_mod.TPUJOBS, job)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded after {timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        store.stop_watchers()
        n_copies = copies.stop()

    durations = timer.snapshot()
    syncs = len(durations)
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": syncs,
        "syncs_per_sec": round(syncs / convergence, 1),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "deepcopies_per_sync": round(n_copies / max(1, syncs), 1),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
    }


def run_tenant_bench(tenants: int, jobs_per_tenant: int, workers: int,
                     threadiness: int, timeout: float,
                     chips_per_job: int = 4,
                     kubelet_tick: float = 0.01,
                     stagger: float = 0.2) -> Dict:
    """Multi-tenant contention scenario: ``tenants`` queues over ONE
    cohort, each with nominal quota for exactly one job, all submitting
    ``jobs_per_tenant`` jobs. Tenants 0..N-2 submit at t0 and borrow
    the idle cohort capacity; the LAST tenant submits ``stagger``
    seconds later, so its nominal demand arrives against a fully
    borrowed cohort and must be satisfied by reclaim preemptions.

    Reports per-queue admission wait (job submit -> SliceGroup
    Inqueue) and reclaim counts on top of the run_bench-style
    convergence numbers."""
    from tf_operator_tpu.api.types import (
        ClusterQueue,
        ClusterQueueSpec,
        TenantQueue,
        TenantQueueSpec,
    )
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.controller.quota import TenantQueueManager
    from tf_operator_tpu.runtime import metrics

    store = Store()
    total_chips = tenants * chips_per_job
    quota = TenantQueueManager(store)
    gang = SliceGangScheduler(store, total_chips=total_chips, quota=quota)
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE)
    queues = [f"tenant-{t}" for t in range(tenants)]
    for q in queues:
        cq = ClusterQueue(spec=ClusterQueueSpec(
            nominal_chips=chips_per_job, cohort="bench"))
        cq.metadata.name = f"cq-{q}"
        cq.metadata.namespace = ""
        store.create(store_mod.CLUSTERQUEUES, cq)
        tq = TenantQueue(spec=TenantQueueSpec(cluster_queue=f"cq-{q}"))
        tq.metadata.name = q
        tq.metadata.namespace = NAMESPACE
        store.create(store_mod.TENANTQUEUES, tq)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    timer = _SyncTimer(controller)
    # Borrowers must still be running when the late tenant's demand
    # arrives, or there is nothing to reclaim; the wide margin keeps
    # the reclaim deterministic on slow shared CI.
    kubelet = FakeKubelet(store, tick=kubelet_tick,
                          admitted=group_admitted,
                          min_run_seconds=stagger + 1.0)

    # submit time per job + first-Inqueue time per group, for the
    # per-queue admission-wait numbers (wall clock, one process).
    submit_t: Dict[str, float] = {}
    inqueue_t: Dict[str, float] = {}
    inqueue_lock = threading.Lock()

    def on_group_event(event_type: str, group) -> None:
        if group.status.phase in (PHASE_INQUEUE, PHASE_RUNNING):
            with inqueue_lock:
                inqueue_t.setdefault(group.metadata.name,
                                     time.perf_counter())

    watcher = store.watch(store_mod.SLICEGROUPS, on_group_event)
    reclaims_before = {q: metrics.quota_reclaims.value(queue=q)
                       for q in queues}

    def submit(tenant: int, index: int) -> None:
        q = queues[tenant]
        name = f"bench-{tenant:02d}-{index:03d}"
        job = testutil.new_tpujob(worker=workers, name=name,
                                  namespace=NAMESPACE)
        job.spec.slice.accelerator = f"v5e-{chips_per_job}"
        job.spec.queue_name = q
        submit_t[name] = time.perf_counter()
        store.create(store_mod.TPUJOBS, job)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    total_jobs = tenants * jobs_per_tenant
    try:
        for t in range(tenants - 1):
            for i in range(jobs_per_tenant):
                submit(t, i)
        time.sleep(stagger)  # the late tenant's demand forces reclaim
        for i in range(jobs_per_tenant):
            submit(tenants - 1, i)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= total_jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{total_jobs} jobs Succeeded after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        watcher.stop()
        store.stop_watchers()

    per_queue = {}
    reclaims_total = 0
    for t, q in enumerate(queues):
        waits = []
        for i in range(jobs_per_tenant):
            name = f"bench-{t:02d}-{i:03d}"
            if name in submit_t and name in inqueue_t:
                waits.append(inqueue_t[name] - submit_t[name])
        reclaims = int(metrics.quota_reclaims.value(queue=q)
                       - reclaims_before[q])
        reclaims_total += reclaims
        per_queue[q] = {
            "jobs": jobs_per_tenant,
            "admission_wait_mean_ms": round(
                sum(waits) / len(waits) * 1e3, 3) if waits else None,
            "admission_wait_max_ms": round(
                max(waits) * 1e3, 3) if waits else None,
            "reclaims": reclaims,
        }
    durations = timer.snapshot()
    syncs = len(durations)
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(total_jobs / convergence, 2),
        "syncs": syncs,
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "tenants": tenants,
        "jobs_per_tenant": jobs_per_tenant,
        "jobs": total_jobs,
        "workers_per_job": workers,
        "pods": total_jobs * workers,
        "chips_per_job": chips_per_job,
        "cohort_chips": total_chips,
        "threadiness": threadiness,
        "reclaims_total": reclaims_total,
        "per_queue": per_queue,
    }


class CkptFakeKubelet(FakeKubelet):
    """FakeKubelet that also plays the checkpointing WORKER + node agent
    (the data-plane relay the local backend provides in production):
    Running pods advance one training step per tick, publish periodic
    saves and barrier acks as CheckpointRecords, and a recreated pod
    resumes from the TPUJOB_RESTORE_STEP env the controller rendered —
    so the disruption scenario measures the full save-before-evict /
    restore-with-identity loop with no subprocess in it."""

    def __init__(self, store: Store, steps: int, tick: float = 0.01,
                 admitted=None, save_interval: int = 20):
        super().__init__(store, tick=tick, admitted=admitted)
        self.steps = steps
        self.save_interval = save_interval
        # (ns, pod) -> training progress of the CURRENT incarnation
        # (keyed by uid so a recreate re-reads its restore env).
        self._progress: Dict[Tuple[str, str, str], int] = {}
        self._acked: Dict[Tuple[str, str, str], str] = {}

    def run(self) -> None:  # overrides FakeKubelet.run
        from tf_operator_tpu.api.types import (
            CheckpointRecord,
            CheckpointRecordStatus,
        )

        while not self._stop.is_set():
            pods = self.store.list(store_mod.PODS, namespace=NAMESPACE)
            for pod in pods:
                if pod.status.phase == PodPhase.PENDING:
                    job_name = pod.metadata.labels.get(
                        constants.LABEL_JOB_NAME, "")
                    if (self.admitted is not None
                            and not self.admitted(pod.metadata.namespace,
                                                  job_name)):
                        continue
                    self._start(pod)
                elif pod.status.phase == PodPhase.RUNNING:
                    self._step(pod, CheckpointRecord,
                               CheckpointRecordStatus)
            self._stop.wait(self.tick)

    def _key(self, pod) -> Tuple[str, str, str]:
        return (pod.metadata.namespace, pod.metadata.name,
                pod.metadata.uid)

    def _start(self, pod) -> None:
        restore = 0
        for c in pod.spec.containers:
            if constants.ENV_RESTORE_STEP in c.env:
                restore = int(c.env[constants.ENV_RESTORE_STEP])
        self._progress[self._key(pod)] = restore
        patch = Pod(metadata=ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace))
        patch.status = PodStatus(phase=PodPhase.RUNNING,
                                 start_time=testutil.now())
        try:
            self.store.update_status(store_mod.PODS, patch)
        except (store_mod.NotFoundError, store_mod.ConflictError):
            pass

    def _step(self, pod, record_cls, status_cls) -> None:
        key = self._key(pod)
        if key not in self._progress:
            self._start(pod)  # Running before we saw it Pending
            return
        self._progress[key] += 1
        progress = self._progress[key]
        notice = pod.metadata.annotations.get(
            constants.ANNOTATION_PREEMPT_NOTICE, "")
        barrier = ""
        if notice and self._acked.get(key) != notice:
            barrier = json.loads(notice).get("barrier", "")
        periodic = progress % self.save_interval == 0
        if barrier or periodic or progress >= self.steps:
            self._publish(pod, progress, barrier, record_cls, status_cls)
            if barrier:
                self._acked[key] = notice
        if progress >= self.steps:
            patch = Pod(metadata=ObjectMeta(
                name=pod.metadata.name,
                namespace=pod.metadata.namespace))
            patch.status = PodStatus(
                phase=PodPhase.SUCCEEDED, start_time=testutil.now(),
                container_statuses=[ContainerStatus(
                    name=constants.DEFAULT_CONTAINER_NAME,
                    state="Terminated", exit_code=0)])
            try:
                self.store.update_status(store_mod.PODS, patch)
            except (store_mod.NotFoundError, store_mod.ConflictError):
                pass

    def _publish(self, pod, progress: int, barrier: str,
                 record_cls, status_cls) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        status = status_cls(step=progress, progress_step=progress,
                            barrier_id=barrier, directory="/bench/ckpt",
                            save_seconds=0.001,
                            updated_at=testutil.now())
        try:
            existing = self.store.try_get(store_mod.CHECKPOINTRECORDS,
                                          ns, name)
            if existing is None:
                self.store.create(store_mod.CHECKPOINTRECORDS, record_cls(
                    metadata=ObjectMeta(
                        name=name, namespace=ns,
                        labels={k: v
                                for k, v in pod.metadata.labels.items()},
                        owner_references=[r.deepcopy() for r in
                                          pod.metadata.owner_references]),
                    status=status))
            else:
                existing.status = status
                self.store.update_status(store_mod.CHECKPOINTRECORDS,
                                         existing)
        except (store_mod.AlreadyExistsError, store_mod.ConflictError,
                store_mod.NotFoundError):
            pass  # raced; next periodic publish lands


def run_disruption_bench(jobs: int, workers: int, threadiness: int,
                         timeout: float, disruptions: int,
                         steps: int = 80, save_interval: int = 20,
                         chips_per_job: int = 4,
                         barrier_timeout: float = 10.0,
                         kubelet_tick: float = 0.01) -> Dict:
    """Disruption/goodput scenario: checkpointing fake jobs under
    injected drains. Each disruption takes the slice-health path —
    ``ready_to_evict`` (opens the save-before-evict barrier), evict the
    gang's pods once it answers True, ``gang.displace`` — against a live
    CheckpointCoordinator; the rebound pods restore from the
    barrier-committed step. Reports barrier outcomes, steps lost, and
    the goodput ratio on top of the convergence numbers."""
    from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.api.types import CheckpointPolicy
    from tf_operator_tpu.runtime import metrics

    store = Store()
    ckpt = CheckpointCoordinator(store).start()
    gang = SliceGangScheduler(store, total_chips=None, ckpt=ckpt)
    ckpt.on_ack = gang.readmit
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE, ckpt=ckpt)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    timer = _SyncTimer(controller)
    kubelet = CkptFakeKubelet(store, steps=steps, tick=kubelet_tick,
                              admitted=group_admitted,
                              save_interval=save_interval)

    acked_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="acked")
    timeout_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="timeout")
    lost_sum_before = metrics.steps_lost_per_disruption.sum_value(
        job_namespace=NAMESPACE)
    lost_n_before = metrics.steps_lost_per_disruption.count_value(
        job_namespace=NAMESPACE)

    injected = [0]
    disruptor_stop = threading.Event()

    def disrupt() -> None:
        """One disruption at a time, round-robin over live gangs: open
        the barrier, then evict + displace the moment it completes —
        the slice-health drain path, level-triggered just like it."""
        cursor = 0
        in_flight: Optional[str] = None
        while not disruptor_stop.is_set() and injected[0] < disruptions:
            target = in_flight
            if target is None:
                live = sorted(
                    g.metadata.name
                    for g in store.list(store_mod.SLICEGROUPS,
                                        namespace=NAMESPACE)
                    if g.status.phase in (PHASE_INQUEUE, PHASE_RUNNING)
                    and not g.status.displaced_reason)
                if not live:
                    disruptor_stop.wait(kubelet_tick)
                    continue
                target = live[cursor % len(live)]
                cursor += 1
            if ckpt.ready_to_evict(NAMESPACE, target,
                                   "bench disruption"):
                for p in store.list(store_mod.PODS, namespace=NAMESPACE,
                                    selector={constants.LABEL_JOB_NAME:
                                              target}):
                    if p.status.phase not in ("Succeeded", "Failed"):
                        store.try_delete(store_mod.PODS, NAMESPACE,
                                         p.metadata.name)
                gang.displace(NAMESPACE, target, "bench disruption")
                injected[0] += 1
                in_flight = None
            else:
                in_flight = target  # barrier open; re-consult next tick
            disruptor_stop.wait(kubelet_tick)

    disruptor = threading.Thread(target=disrupt, name="disruptor",
                                 daemon=True)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers,
                                      name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            job.spec.slice.accelerator = f"v5e-{chips_per_job}"
            job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
                enabled=True, directory="/bench/ckpt",
                interval_steps=save_interval,
                barrier_timeout_seconds=barrier_timeout)
            store.create(store_mod.TPUJOBS, job)
        disruptor.start()

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= jobs and injected[0] >= disruptions:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded, "
                    f"{injected[0]}/{disruptions} disruptions after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        disruptor_stop.set()
        kubelet.stop()
        controller.stop()
        ckpt.stop()
        store.stop_watchers()

    goodputs = [metrics.job_goodput_ratio.value(
        job_namespace=NAMESPACE, job=f"bench-{i:04d}")
        for i in range(jobs)]
    goodputs = [g for g in goodputs if g > 0.0]
    lost_total = (metrics.steps_lost_per_disruption.sum_value(
        job_namespace=NAMESPACE) - lost_sum_before)
    lost_n = (metrics.steps_lost_per_disruption.count_value(
        job_namespace=NAMESPACE) - lost_n_before)
    restored = [r.status.restored_from_step
                for r in store.list(store_mod.CHECKPOINTRECORDS,
                                    namespace=NAMESPACE)
                if r.status.restored_from_step is not None]
    durations = timer.snapshot()
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": len(durations),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "steps_per_job": steps,
        "save_interval_steps": save_interval,
        "disruptions": disruptions,
        "disruptions_injected": injected[0],
        "barriers_acked": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="acked") - acked_before),
        "barriers_timeout": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="timeout")
            - timeout_before),
        "steps_lost_total": int(lost_total),
        "steps_lost_per_disruption_mean": round(
            lost_total / lost_n, 2) if lost_n else 0.0,
        "goodput_ratio_mean": round(
            sum(goodputs) / len(goodputs), 4) if goodputs else None,
        "goodput_ratio_min": round(min(goodputs), 4) if goodputs else None,
        "restores_observed": len(restored),
    }


def _environment() -> Dict:
    """Environment fingerprint fields (auditable round-over-round):
    jax version + platform/chip kind when jax is importable, host facts
    always. Importing jax is optional — the control plane needs none of
    it and smoke environments may not have it."""
    env = {
        "python": _platform.python_version(),
        "machine": _platform.machine(),
        "system": _platform.system(),
    }
    try:
        import jax

        env["jax_version"] = jax.__version__
        d = jax.devices()[0]
        env["platform"] = d.platform
        env["chip_kind"] = getattr(d, "device_kind", "") or d.platform
    except Exception:
        env["jax_version"] = None
        env["platform"] = "none"
        env["chip_kind"] = "none"
    return env


def config_fingerprint(config: Dict) -> str:
    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=200,
                   help="total jobs (plain scenario) or jobs PER TENANT "
                        "(--tenants scenario)")
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--threadiness", type=int, default=4)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--kubelet-tick", type=float, default=0.01)
    p.add_argument("--tenants", type=int, default=0,
                   help="N>0 switches to the multi-tenant contention "
                        "scenario: N tenant queues over one cohort, "
                        "gang admission + quota on, per-queue "
                        "admission-wait and reclaim counts in the "
                        "artifact")
    p.add_argument("--chips-per-job", type=int, default=4,
                   help="(--tenants) slice size per job = per-queue "
                        "nominal quota")
    p.add_argument("--disruptions", type=int, default=0,
                   help="N>0 switches to the disruption/goodput "
                        "scenario: checkpointing fake jobs with N "
                        "injected drains through the save-before-evict "
                        "barrier (controller/ckpt.py); barrier "
                        "outcomes, steps-lost, and goodput ratio in "
                        "the artifact")
    p.add_argument("--steps", type=int, default=80,
                   help="(--disruptions) fake training steps per job")
    p.add_argument("--save-interval", type=int, default=20,
                   help="(--disruptions) periodic-save cadence in steps")
    args = p.parse_args(argv)

    config = {"jobs": args.jobs, "workers": args.workers,
              "threadiness": args.threadiness,
              "kubelet_tick": args.kubelet_tick}
    if args.tenants > 0:
        config.update({"tenants": args.tenants,
                       "chips_per_job": args.chips_per_job})
        metric = (f"controlplane_tenant_convergence_jobs_per_sec"
                  f"[{args.tenants}t x {args.jobs}x{args.workers}]")
    elif args.disruptions > 0:
        config.update({"disruptions": args.disruptions,
                       "steps": args.steps,
                       "save_interval": args.save_interval})
        metric = (f"controlplane_disruption_goodput_ratio"
                  f"[{args.jobs}x{args.workers} d{args.disruptions}]")
    else:
        metric = (f"controlplane_convergence_jobs_per_sec"
                  f"[{args.jobs}x{args.workers}]")
    try:
        if args.tenants > 0:
            result = run_tenant_bench(
                args.tenants, args.jobs, args.workers, args.threadiness,
                args.timeout, chips_per_job=args.chips_per_job,
                kubelet_tick=args.kubelet_tick)
        elif args.disruptions > 0:
            result = run_disruption_bench(
                args.jobs, args.workers, args.threadiness, args.timeout,
                disruptions=args.disruptions, steps=args.steps,
                save_interval=args.save_interval,
                kubelet_tick=args.kubelet_tick)
        else:
            result = run_bench(args.jobs, args.workers, args.threadiness,
                               args.timeout,
                               kubelet_tick=args.kubelet_tick)
        if args.disruptions > 0:
            value, unit = result.get("goodput_ratio_mean"), "ratio"
        else:
            value, unit = result["jobs_per_sec"], "jobs/sec"
        print(json.dumps({
            "metric": metric,
            "value": value,
            "unit": unit,
            **result,
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        return 0
    except Exception as e:  # one JSON line, even on failure
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": "jobs/sec",
            "error": f"{type(e).__name__}: {e}",
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
