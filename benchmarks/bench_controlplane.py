"""Control-plane churn benchmark: reconcile-path throughput at scale.

The training-path headline (bench.py) is HBM-bound and exhausted; this
harness watches the OTHER hot path — the reconcile loop — at the fleet
shapes the pod-scale papers describe (one controller owning thousands
of pods across hundreds of gangs).

Shape: create N TPUJobs x M worker pods against the in-process Store
(API-server analog, no data plane), with a fake kubelet driving every
pod Pending -> Running -> Succeeded. The controller must observe the
churn, create pods/endpoints, roll up statuses, and converge every job
to Succeeded. Reported:

- convergence_seconds: first job create -> last job Succeeded
- jobs_per_sec: N / convergence_seconds (the headline; the acceptance
  target is >=5x over the pre-PR controller at 200 jobs x 16 pods)
- syncs + syncs_per_sec and exact p50/p99 reconcile latency (measured
  around sync_tpujob, not bucketized)
- deepcopies_per_sync: ApiObject.deepcopy calls / syncs — the
  per-sync allocation proxy (store snapshots + single-list syncs are
  exactly the levers that move it)

Prints exactly ONE JSON line (bench.py artifact discipline), with the
environment fingerprint satellite fields (jax version, platform,
config fingerprint) so round-over-round medians are auditable.

Usage:
    python benchmarks/bench_controlplane.py                  # 200x16
    python benchmarks/bench_controlplane.py --jobs 5 --workers 2  # smoke
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import platform as _platform
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu import testutil  # noqa: E402
from tf_operator_tpu.api.types import (  # noqa: E402
    ContainerStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodStatus,
)
from tf_operator_tpu.api import constants  # noqa: E402
from tf_operator_tpu.api.serde import ApiObject  # noqa: E402
from tf_operator_tpu.controller import conditions as cond  # noqa: E402
from tf_operator_tpu.controller.tpu_controller import (  # noqa: E402
    TPUJobController,
)
from tf_operator_tpu.runtime import store as store_mod  # noqa: E402
from tf_operator_tpu.runtime.store import Store  # noqa: E402

NAMESPACE = "bench"


class FakeKubelet(threading.Thread):
    """Drives pod phases like a node agent: every tick, Pending pods
    start Running and Running pods complete with exit 0. One phase per
    tick so the controller observes the full lifecycle churn.

    ``admitted``: optional (namespace, job_name) -> bool gate — the
    gang-gated data-plane analog for the tenant scenario: a Pending pod
    only starts once its SliceGroup is admitted (without it, pods of
    quota-held gangs would run anyway and the contention measurement
    would be fiction).

    ``min_run_seconds``: hold Running pods at least this long before
    completing them — the tenant scenario needs borrowers still
    RUNNING when the late tenant's nominal demand arrives, or there is
    nothing to reclaim."""

    def __init__(self, store: Store, tick: float = 0.01, admitted=None,
                 min_run_seconds: float = 0.0):
        super().__init__(name="fake-kubelet", daemon=True)
        self.store = store
        self.tick = tick
        self.admitted = admitted
        self.min_run_seconds = min_run_seconds
        self._run_since: Dict[Tuple[str, str], float] = {}
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            transitions = self.store.project(
                store_mod.PODS,
                lambda p: ((p.metadata.namespace, p.metadata.name,
                            p.status.phase,
                            p.metadata.labels.get(
                                constants.LABEL_JOB_NAME, ""))
                           if p.status.phase in (PodPhase.PENDING,
                                                 PodPhase.RUNNING)
                           else None),
                namespace=NAMESPACE)
            now = time.perf_counter()
            for ns, name, phase, job_name in transitions:
                patch = Pod(metadata=ObjectMeta(name=name, namespace=ns))
                if phase == PodPhase.PENDING:
                    if (self.admitted is not None
                            and not self.admitted(ns, job_name)):
                        continue  # gang gate: held until admission
                    self._run_since[(ns, name)] = now
                    patch.status = PodStatus(phase=PodPhase.RUNNING,
                                             start_time=testutil.now())
                elif (self.min_run_seconds
                        and now - self._run_since.get((ns, name), 0.0)
                        < self.min_run_seconds):
                    continue  # still inside its minimum runtime
                else:
                    patch.status = PodStatus(
                        phase=PodPhase.SUCCEEDED,
                        start_time=testutil.now(),
                        container_statuses=[ContainerStatus(
                            name=constants.DEFAULT_CONTAINER_NAME,
                            state="Terminated", exit_code=0)])
                try:
                    self.store.update_status(store_mod.PODS, patch)
                except (store_mod.NotFoundError, store_mod.ConflictError):
                    pass  # reaped or raced by the controller; benign
            self._stop.wait(self.tick)


class _SyncTimer:
    """Wraps sync_tpujob to count syncs and record exact durations
    (the metrics histogram is bucketized; p99 wants raw samples)."""

    def __init__(self, controller: TPUJobController):
        self._inner = controller.sync_tpujob
        self.durations: List[float] = []
        self._lock = threading.Lock()
        controller.sync_tpujob = self  # type: ignore[assignment]

    def __call__(self, key: str) -> None:
        t0 = time.perf_counter()
        try:
            self._inner(key)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.durations.append(dt)

    def snapshot(self) -> List[float]:
        with self._lock:
            return list(self.durations)


class _DeepcopyCounter:
    """Counts ApiObject.deepcopy calls — the dominant per-sync
    allocation source in the reconcile path."""

    def __init__(self):
        self.count = 0
        self._orig = ApiObject.deepcopy
        counter = self

        def counted(obj):
            counter.count += 1
            return counter._orig(obj)

        ApiObject.deepcopy = counted

    def stop(self) -> int:
        ApiObject.deepcopy = self._orig
        return self.count


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


def run_bench(jobs: int, workers: int, threadiness: int,
              timeout: float, kubelet_tick: float = 0.01) -> Dict:
    """Returns the artifact dict (not yet JSON-encoded). Raises
    TimeoutError if the fleet does not converge within ``timeout``."""
    store = Store()
    controller = TPUJobController(store, namespace=NAMESPACE)
    timer = _SyncTimer(controller)
    copies = _DeepcopyCounter()
    kubelet = FakeKubelet(store, tick=kubelet_tick)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers, name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            store.create(store_mod.TPUJOBS, job)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded after {timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        store.stop_watchers()
        n_copies = copies.stop()

    durations = timer.snapshot()
    syncs = len(durations)
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": syncs,
        "syncs_per_sec": round(syncs / convergence, 1),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "deepcopies_per_sync": round(n_copies / max(1, syncs), 1),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
    }


def run_tenant_bench(tenants: int, jobs_per_tenant: int, workers: int,
                     threadiness: int, timeout: float,
                     chips_per_job: int = 4,
                     kubelet_tick: float = 0.01,
                     stagger: float = 0.2) -> Dict:
    """Multi-tenant contention scenario: ``tenants`` queues over ONE
    cohort, each with nominal quota for exactly one job, all submitting
    ``jobs_per_tenant`` jobs. Tenants 0..N-2 submit at t0 and borrow
    the idle cohort capacity; the LAST tenant submits ``stagger``
    seconds later, so its nominal demand arrives against a fully
    borrowed cohort and must be satisfied by reclaim preemptions.

    Reports per-queue admission wait (job submit -> SliceGroup
    Inqueue) and reclaim counts on top of the run_bench-style
    convergence numbers."""
    from tf_operator_tpu.api.types import (
        ClusterQueue,
        ClusterQueueSpec,
        TenantQueue,
        TenantQueueSpec,
    )
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.controller.quota import TenantQueueManager
    from tf_operator_tpu.runtime import metrics

    store = Store()
    total_chips = tenants * chips_per_job
    quota = TenantQueueManager(store)
    gang = SliceGangScheduler(store, total_chips=total_chips, quota=quota)
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE)
    queues = [f"tenant-{t}" for t in range(tenants)]
    for q in queues:
        cq = ClusterQueue(spec=ClusterQueueSpec(
            nominal_chips=chips_per_job, cohort="bench"))
        cq.metadata.name = f"cq-{q}"
        cq.metadata.namespace = ""
        store.create(store_mod.CLUSTERQUEUES, cq)
        tq = TenantQueue(spec=TenantQueueSpec(cluster_queue=f"cq-{q}"))
        tq.metadata.name = q
        tq.metadata.namespace = NAMESPACE
        store.create(store_mod.TENANTQUEUES, tq)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    timer = _SyncTimer(controller)
    # Borrowers must still be running when the late tenant's demand
    # arrives, or there is nothing to reclaim; the wide margin keeps
    # the reclaim deterministic on slow shared CI.
    kubelet = FakeKubelet(store, tick=kubelet_tick,
                          admitted=group_admitted,
                          min_run_seconds=stagger + 1.0)

    # submit time per job + first-Inqueue time per group, for the
    # per-queue admission-wait numbers (wall clock, one process).
    submit_t: Dict[str, float] = {}
    inqueue_t: Dict[str, float] = {}
    inqueue_lock = threading.Lock()

    def on_group_event(event_type: str, group) -> None:
        if group.status.phase in (PHASE_INQUEUE, PHASE_RUNNING):
            with inqueue_lock:
                inqueue_t.setdefault(group.metadata.name,
                                     time.perf_counter())

    watcher = store.watch(store_mod.SLICEGROUPS, on_group_event)
    reclaims_before = {q: metrics.quota_reclaims.value(queue=q)
                       for q in queues}

    def submit(tenant: int, index: int) -> None:
        q = queues[tenant]
        name = f"bench-{tenant:02d}-{index:03d}"
        job = testutil.new_tpujob(worker=workers, name=name,
                                  namespace=NAMESPACE)
        job.spec.slice.accelerator = f"v5e-{chips_per_job}"
        job.spec.queue_name = q
        submit_t[name] = time.perf_counter()
        store.create(store_mod.TPUJOBS, job)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    total_jobs = tenants * jobs_per_tenant
    try:
        for t in range(tenants - 1):
            for i in range(jobs_per_tenant):
                submit(t, i)
        time.sleep(stagger)  # the late tenant's demand forces reclaim
        for i in range(jobs_per_tenant):
            submit(tenants - 1, i)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= total_jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{total_jobs} jobs Succeeded after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        watcher.stop()
        store.stop_watchers()

    per_queue = {}
    reclaims_total = 0
    for t, q in enumerate(queues):
        waits = []
        for i in range(jobs_per_tenant):
            name = f"bench-{t:02d}-{i:03d}"
            if name in submit_t and name in inqueue_t:
                waits.append(inqueue_t[name] - submit_t[name])
        reclaims = int(metrics.quota_reclaims.value(queue=q)
                       - reclaims_before[q])
        reclaims_total += reclaims
        per_queue[q] = {
            "jobs": jobs_per_tenant,
            "admission_wait_mean_ms": round(
                sum(waits) / len(waits) * 1e3, 3) if waits else None,
            "admission_wait_max_ms": round(
                max(waits) * 1e3, 3) if waits else None,
            "reclaims": reclaims,
        }
    durations = timer.snapshot()
    syncs = len(durations)
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(total_jobs / convergence, 2),
        "syncs": syncs,
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "tenants": tenants,
        "jobs_per_tenant": jobs_per_tenant,
        "jobs": total_jobs,
        "workers_per_job": workers,
        "pods": total_jobs * workers,
        "chips_per_job": chips_per_job,
        "cohort_chips": total_chips,
        "threadiness": threadiness,
        "reclaims_total": reclaims_total,
        "per_queue": per_queue,
    }


def _environment() -> Dict:
    """Environment fingerprint fields (auditable round-over-round):
    jax version + platform/chip kind when jax is importable, host facts
    always. Importing jax is optional — the control plane needs none of
    it and smoke environments may not have it."""
    env = {
        "python": _platform.python_version(),
        "machine": _platform.machine(),
        "system": _platform.system(),
    }
    try:
        import jax

        env["jax_version"] = jax.__version__
        d = jax.devices()[0]
        env["platform"] = d.platform
        env["chip_kind"] = getattr(d, "device_kind", "") or d.platform
    except Exception:
        env["jax_version"] = None
        env["platform"] = "none"
        env["chip_kind"] = "none"
    return env


def config_fingerprint(config: Dict) -> str:
    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=200,
                   help="total jobs (plain scenario) or jobs PER TENANT "
                        "(--tenants scenario)")
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--threadiness", type=int, default=4)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--kubelet-tick", type=float, default=0.01)
    p.add_argument("--tenants", type=int, default=0,
                   help="N>0 switches to the multi-tenant contention "
                        "scenario: N tenant queues over one cohort, "
                        "gang admission + quota on, per-queue "
                        "admission-wait and reclaim counts in the "
                        "artifact")
    p.add_argument("--chips-per-job", type=int, default=4,
                   help="(--tenants) slice size per job = per-queue "
                        "nominal quota")
    args = p.parse_args(argv)

    config = {"jobs": args.jobs, "workers": args.workers,
              "threadiness": args.threadiness,
              "kubelet_tick": args.kubelet_tick}
    if args.tenants > 0:
        config.update({"tenants": args.tenants,
                       "chips_per_job": args.chips_per_job})
        metric = (f"controlplane_tenant_convergence_jobs_per_sec"
                  f"[{args.tenants}t x {args.jobs}x{args.workers}]")
    else:
        metric = (f"controlplane_convergence_jobs_per_sec"
                  f"[{args.jobs}x{args.workers}]")
    try:
        if args.tenants > 0:
            result = run_tenant_bench(
                args.tenants, args.jobs, args.workers, args.threadiness,
                args.timeout, chips_per_job=args.chips_per_job,
                kubelet_tick=args.kubelet_tick)
        else:
            result = run_bench(args.jobs, args.workers, args.threadiness,
                               args.timeout,
                               kubelet_tick=args.kubelet_tick)
        print(json.dumps({
            "metric": metric,
            "value": result["jobs_per_sec"],
            "unit": "jobs/sec",
            **result,
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        return 0
    except Exception as e:  # one JSON line, even on failure
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": "jobs/sec",
            "error": f"{type(e).__name__}: {e}",
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
