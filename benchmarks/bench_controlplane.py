"""Control-plane churn benchmark: reconcile-path throughput at scale.

The training-path headline (bench.py) is HBM-bound and exhausted; this
harness watches the OTHER hot path — the reconcile loop — at the fleet
shapes the pod-scale papers describe (one controller owning thousands
of pods across hundreds of gangs).

Shape: create N TPUJobs x M worker pods against the in-process Store
(API-server analog, no data plane), with a fake kubelet driving every
pod Pending -> Running -> Succeeded. The controller must observe the
churn, create pods/endpoints, roll up statuses, and converge every job
to Succeeded. Reported:

- convergence_seconds: first job create -> last job Succeeded
- jobs_per_sec: N / convergence_seconds (the headline; the acceptance
  target is >=5x over the pre-PR controller at 200 jobs x 16 pods)
- syncs + syncs_per_sec and exact p50/p99 reconcile latency (measured
  around sync_tpujob, not bucketized)
- deepcopies_per_sync: ApiObject.deepcopy calls / syncs — the
  per-sync allocation proxy (store snapshots + single-list syncs are
  exactly the levers that move it)

Prints exactly ONE JSON line (bench.py artifact discipline), with the
environment fingerprint satellite fields (jax version, platform,
config fingerprint) so round-over-round medians are auditable.

Usage:
    python benchmarks/bench_controlplane.py                  # 200x16
    python benchmarks/bench_controlplane.py --jobs 5 --workers 2  # smoke
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import platform as _platform
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu import testutil  # noqa: E402
from tf_operator_tpu.api.types import (  # noqa: E402
    ContainerStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodStatus,
)
from tf_operator_tpu.api import constants  # noqa: E402
from tf_operator_tpu.api.serde import ApiObject  # noqa: E402
from tf_operator_tpu.controller import conditions as cond  # noqa: E402
from tf_operator_tpu.controller.tpu_controller import (  # noqa: E402
    TPUJobController,
)
from tf_operator_tpu.runtime import store as store_mod  # noqa: E402
from tf_operator_tpu.runtime import trace as trace_mod  # noqa: E402
from tf_operator_tpu.runtime.store import Store  # noqa: E402

NAMESPACE = "bench"

# Span/phase names the flight recorder attributes one sync's time to
# (runtime/trace.py instrumentation sites) — the artifact's
# "where did the time go" keys.
SYNC_BREAKDOWN_SPANS = ("job.fetch", "spec.validate", "pods.list",
                        "gang.sync", "ckpt.sync", "reconcile.replicas",
                        "status.rollup", "status.diff", "status.write",
                        "finalize")


def _phase_attribution(totals: Dict[str, float],
                       convergence_seconds: float) -> Dict:
    """The per-phase wall-clock attribution block (docs/benchmarks.md
    "Phase attribution"). Phase seconds are CUMULATIVE across sync
    workers and queued items, so with threadiness N the wall-clock
    coverage can legitimately exceed 100%; the acceptance floor is
    >=90% — below that, convergence time is going somewhere the
    recorder cannot see and the next perf PR flies blind."""
    sync_s = totals.get("sync", 0.0)
    attributed_in_sync = sum(totals.get(k, 0.0)
                             for k in SYNC_BREAKDOWN_SPANS)
    phases = {
        "queue_wait_s": round(totals.get("queue_wait", 0.0), 4),
        "sync_s": round(sync_s, 4),
        "api_retry_s": round(totals.get("api_retry", 0.0), 4),
        "barrier_wait_s": round(totals.get("barrier_wait", 0.0), 4),
        "binder_s": round(totals.get("binder.pass", 0.0), 4),
    }
    total = sum(phases.values())
    return {
        **phases,
        "sync_breakdown_s": {k: round(totals.get(k, 0.0), 4)
                             for k in SYNC_BREAKDOWN_SPANS},
        "sync_attributed_pct": (
            round(100.0 * attributed_in_sync / sync_s, 1)
            if sync_s > 0 else None),
        "wallclock_attributed_pct": (
            round(100.0 * total / convergence_seconds, 1)
            if convergence_seconds > 0 else None),
    }


class FakeKubelet(threading.Thread):
    """Drives pod phases like a node agent: every tick, Pending pods
    start Running and Running pods complete with exit 0. One phase per
    tick so the controller observes the full lifecycle churn.

    ``admitted``: optional (namespace, job_name) -> bool gate — the
    gang-gated data-plane analog for the tenant scenario: a Pending pod
    only starts once its SliceGroup is admitted (without it, pods of
    quota-held gangs would run anyway and the contention measurement
    would be fiction).

    ``min_run_seconds``: hold Running pods at least this long before
    completing them — the tenant scenario needs borrowers still
    RUNNING when the late tenant's nominal demand arrives, or there is
    nothing to reclaim."""

    def __init__(self, store: Store, tick: float = 0.01, admitted=None,
                 min_run_seconds: float = 0.0):
        super().__init__(name="fake-kubelet", daemon=True)
        self.store = store
        self.tick = tick
        self.admitted = admitted
        self.min_run_seconds = min_run_seconds
        self._run_since: Dict[Tuple[str, str], float] = {}
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            transitions = self.store.project(
                store_mod.PODS,
                lambda p: ((p.metadata.namespace, p.metadata.name,
                            p.status.phase,
                            p.metadata.labels.get(
                                constants.LABEL_JOB_NAME, ""))
                           if p.status.phase in (PodPhase.PENDING,
                                                 PodPhase.RUNNING)
                           else None),
                namespace=NAMESPACE)
            now = time.perf_counter()
            for ns, name, phase, job_name in transitions:
                patch = Pod(metadata=ObjectMeta(name=name, namespace=ns))
                if phase == PodPhase.PENDING:
                    if (self.admitted is not None
                            and not self.admitted(ns, job_name)):
                        continue  # gang gate: held until admission
                    self._run_since[(ns, name)] = now
                    patch.status = PodStatus(phase=PodPhase.RUNNING,
                                             start_time=testutil.now())
                elif (self.min_run_seconds
                        and now - self._run_since.get((ns, name), 0.0)
                        < self.min_run_seconds):
                    continue  # still inside its minimum runtime
                else:
                    patch.status = PodStatus(
                        phase=PodPhase.SUCCEEDED,
                        start_time=testutil.now(),
                        container_statuses=[ContainerStatus(
                            name=constants.DEFAULT_CONTAINER_NAME,
                            state="Terminated", exit_code=0)])
                try:
                    self.store.update_status(store_mod.PODS, patch)
                except (store_mod.NotFoundError, store_mod.ConflictError):
                    pass  # reaped or raced by the controller; benign
            self._stop.wait(self.tick)


class _SyncTimer:
    """Wraps sync_tpujob to count syncs and record exact durations
    (the metrics histogram is bucketized; p99 wants raw samples)."""

    def __init__(self, controller: TPUJobController):
        self._inner = controller.sync_tpujob
        self.durations: List[float] = []
        self._lock = threading.Lock()
        controller.sync_tpujob = self  # type: ignore[assignment]

    def __call__(self, key: str) -> None:
        t0 = time.perf_counter()
        try:
            self._inner(key)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.durations.append(dt)

    def snapshot(self) -> List[float]:
        with self._lock:
            return list(self.durations)


class _DeepcopyCounter:
    """Counts ApiObject.deepcopy calls — the dominant per-sync
    allocation source in the reconcile path."""

    def __init__(self):
        self.count = 0
        self._orig = ApiObject.deepcopy
        counter = self

        def counted(obj):
            counter.count += 1
            return counter._orig(obj)

        ApiObject.deepcopy = counted

    def stop(self) -> int:
        ApiObject.deepcopy = self._orig
        return self.count


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[idx]


def run_bench(jobs: int, workers: int, threadiness: int,
              timeout: float, kubelet_tick: float = 0.01,
              trace: bool = True) -> Dict:
    """Returns the artifact dict (not yet JSON-encoded). Raises
    TimeoutError if the fleet does not converge within ``timeout``.

    ``trace=True`` (the default) runs the fleet with the flight
    recorder on and adds the ``phase_attribution`` block; ``--no-trace``
    is the baseline half of the tracing-overhead A/B (the delta is the
    recorded cost of tracing — docs/benchmarks.md)."""
    store = Store()
    controller = TPUJobController(store, namespace=NAMESPACE)
    timer = _SyncTimer(controller)
    copies = _DeepcopyCounter()
    kubelet = FakeKubelet(store, tick=kubelet_tick)

    if trace:
        trace_mod.RECORDER.reset()
        trace_mod.configure(True)
    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers, name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            store.create(store_mod.TPUJOBS, job)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded after {timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        store.stop_watchers()
        n_copies = copies.stop()
        if trace:
            trace_mod.configure(False)

    durations = timer.snapshot()
    syncs = len(durations)
    result = {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": syncs,
        "syncs_per_sec": round(syncs / convergence, 1),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "deepcopies_per_sync": round(n_copies / max(1, syncs), 1),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "tracing": trace,
    }
    if trace:
        result["phase_attribution"] = _phase_attribution(
            trace_mod.RECORDER.phase_totals(), convergence)
    return result


class _OwnershipRecorder:
    """_SyncTimer variant for the sharded scenario: times syncs AND
    records the ownership evidence — every synced key must hash to the
    syncing controller's shard, and no shard may have two live
    controllers (the no-double-reconcile proof)."""

    def __init__(self, controller: TPUJobController, store: Store,
                 shards: int, durations: List[float],
                 violations: List[str], lock: threading.Lock):
        from tf_operator_tpu.runtime.leaderelection import shard_for

        self._inner = controller.sync_tpujob
        self._controller = controller
        self._store = store
        self._shards = shards
        self._shard_for = shard_for
        self.durations = durations
        self.violations = violations
        self._lock = lock
        controller.sync_tpujob = self  # type: ignore[assignment]

    def __call__(self, key: str) -> None:
        ns, name = key.split("/", 1)
        snap = self._store.get_snapshot(store_mod.TPUJOBS, ns, name)
        if snap is not None:
            owner = self._shard_for(ns, snap.metadata.uid, self._shards)
            if owner != self._controller.shard_index:
                with self._lock:
                    self.violations.append(
                        f"{key} synced by shard "
                        f"{self._controller.shard_index}, owned by "
                        f"shard {owner}")
        t0 = time.perf_counter()
        try:
            self._inner(key)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.durations.append(dt)


class _ShardedReplica:
    """One operator replica of the sharded scenario: a ShardMap whose
    acquisitions build a per-shard TPUJobController resuming from the
    store's watch log (since_rv) plus one resync sweep of the shard's
    jobs — the takeover fast path. A global ``active`` registry proves
    single-ownership: two live controllers on one shard is a recorded
    violation."""

    def __init__(self, name: str, store: Store, shards: int,
                 threadiness: int, durations: List[float],
                 violations: List[str], lock: threading.Lock,
                 active: Dict[int, str],
                 lease_duration: float = 1.0,
                 renew_deadline: float = 0.4,
                 retry_period: float = 0.05,
                 controller_store=None,
                 expectations_timeout: Optional[float] = None):
        from tf_operator_tpu.runtime.leaderelection import (
            ShardMap,
            shard_for,
        )

        self.name = name
        self.store = store
        # Chaos rounds reconcile through a fault-injecting store while
        # the shard leases stay on the healthy base (a flaky lease is a
        # different failure mode than a flaky API).
        self.controller_store = controller_store or store
        self.expectations_timeout = expectations_timeout
        self.shards = shards
        self.threadiness = threadiness
        self.durations = durations
        self.violations = violations
        self.lock = lock
        self.active = active
        self._shard_for = shard_for
        self.controllers: Dict[int, TPUJobController] = {}
        self.map = ShardMap(store, shards, identity=name,
                            namespace=NAMESPACE,
                            lease_duration=lease_duration,
                            renew_deadline=renew_deadline,
                            retry_period=retry_period,
                            on_shard_acquired=self._acquired,
                            on_shard_lost=self._lost)

    def _acquired(self, index: int) -> None:
        with self.lock:
            holder = self.active.get(index)
            if holder is not None:
                self.violations.append(
                    f"shard {index} acquired by {self.name} while "
                    f"{holder} still runs a controller on it "
                    "(double-reconcile window)")
            self.active[index] = self.name
        since_rv = self.store.latest_rv()
        c = TPUJobController(self.controller_store, namespace=NAMESPACE,
                             shard_index=index, shard_count=self.shards)
        if self.expectations_timeout is not None:
            c.expectations._timeout = self.expectations_timeout
        _OwnershipRecorder(c, self.store, self.shards, self.durations,
                           self.violations, self.lock)
        c.run(threadiness=self.threadiness, since_rv=since_rv)
        # Resume covers events AFTER since_rv; one sweep of the shard's
        # current jobs covers everything before it (snapshot walk, no
        # deepcopies).
        for ns, name, _ in self.store.keys(store_mod.TPUJOBS):
            snap = self.store.get_snapshot(store_mod.TPUJOBS, ns, name)
            if (snap is not None
                    and self._shard_for(ns, snap.metadata.uid,
                                        self.shards) == index):
                c.enqueue(f"{ns}/{name}")
        self.controllers[index] = c

    def _lost(self, index: int) -> None:
        c = self.controllers.pop(index, None)
        with self.lock:
            if self.active.get(index) == self.name:
                del self.active[index]
        if c is not None:
            c.stop()

    def crash_shard(self, index: int) -> None:
        """Kill this replica's hold on ``index`` the hard way: elector
        dies renewing nothing (no release — survivors must wait out the
        lease), controller dies with its workqueue/expectations."""
        from tf_operator_tpu.runtime.chaos import crash_controller

        self.map.crash(index)
        c = self.controllers.pop(index, None)
        with self.lock:
            if self.active.get(index) == self.name:
                del self.active[index]
        crash_controller(c)

    def stop(self) -> None:
        self.map.stop()
        for index in list(self.controllers):
            self._lost(index)


def run_sharded_bench(jobs: int, workers: int, shards: int,
                      threadiness: int, timeout: float,
                      kubelet_tick: float = 0.01,
                      kill_shard: bool = True,
                      trace: bool = True,
                      lease_duration: Optional[float] = None,
                      renew_deadline: Optional[float] = None,
                      retry_period: Optional[float] = None) -> Dict:
    """Sharded control-plane scenario (--shards N): the run_bench fleet
    shape against N shard leases. Replica A contends for every shard
    and wins them all; standby replica B contends too and initially
    holds nothing. Each held shard runs a full TPUJobController over
    only its jobs (ownership hash on (namespace, uid)).

    ``kill_shard`` injects the failover: once a third of the fleet has
    converged, one of A's shards is crashed (lease NOT released,
    controller killed abruptly) — B re-acquires it after lease expiry
    and drives the shard's remaining jobs home. The artifact records
    the availability cost (failover_seconds) and the correctness
    evidence (ownership_violations must be empty: every sync on the
    owning shard, never two live controllers per shard).

    The FakeKubelet data plane, job shape, and deepcopy accounting are
    identical to run_bench, so the jobs/sec ratio is apples-to-apples.
    """
    from tf_operator_tpu.runtime.leaderelection import shard_for

    store = Store()
    copies = _DeepcopyCounter()
    kubelet = FakeKubelet(store, tick=kubelet_tick)
    durations: List[float] = []
    violations: List[str] = []
    lock = threading.Lock()
    active: Dict[int, str] = {}
    per_shard_threads = max(1, threadiness // shards)
    # Bench-proportionate lease timings. Small fleets get fast leases
    # so failover is cheap to measure; at the 2kx32 shape the watch
    # fan-out + sync load starves elector threads for whole-second
    # stretches, and a 0.4s renew deadline reads that scheduling jitter
    # as leader death — spurious stepdowns whose takeovers land before
    # the loser's teardown, i.e. manufactured split-brain. Production
    # uses 15/5/3 for the same reason.
    if lease_duration is None:
        big = jobs * workers >= 20_000
        lease_duration = 10.0 if big else 1.0
        renew_deadline = 5.0 if big else 0.4
        retry_period = 0.5 if big else 0.05

    if trace:
        trace_mod.RECORDER.reset()
        trace_mod.configure(True)

    hits0 = store.watch_cache_hits
    misses0 = store.watch_cache_misses
    replica_a = _ShardedReplica("replica-a", store, shards,
                                per_shard_threads, durations, violations,
                                lock, active,
                                lease_duration=lease_duration,
                                renew_deadline=renew_deadline,
                                retry_period=retry_period)
    replica_b = _ShardedReplica("replica-b", store, shards,
                                per_shard_threads, durations, violations,
                                lock, active,
                                lease_duration=lease_duration,
                                renew_deadline=renew_deadline,
                                retry_period=retry_period)
    replica_a.map.start()
    if not replica_a.map.wait_until_held(shards, timeout=30.0):
        raise TimeoutError(
            f"replica A holds {sorted(replica_a.map.held())} of "
            f"{shards} shards after 30s")
    replica_b.map.start()  # standby: contends, acquires nothing yet

    kubelet.start()
    t0 = time.perf_counter()
    killed_shard: Optional[int] = None
    kill_t: Optional[float] = None
    failover_seconds: Optional[float] = None
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers,
                                      name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            store.create(store_mod.TPUJOBS, job)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if (kill_shard and killed_shard is None
                    and succeeded >= max(1, jobs // 3)):
                killed_shard = shards - 1
                kill_t = time.perf_counter()
                replica_a.crash_shard(killed_shard)
            if (killed_shard is not None and failover_seconds is None
                    and killed_shard in replica_b.map.held()):
                failover_seconds = time.perf_counter() - kill_t
            if succeeded >= jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded after {timeout}s "
                    f"(A holds {sorted(replica_a.map.held())}, "
                    f"B holds {sorted(replica_b.map.held())})")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
        if kill_shard and killed_shard is not None and failover_seconds is None:
            # Small fleets can converge before the standby has even
            # waited out the dead leader's lease — that is a fast
            # bench, not a failover bug. Give B the worst-case
            # acquisition window (lease expiry + jittered retries)
            # before declaring the shard orphaned.
            acquire_by = time.perf_counter() + 3 * lease_duration + 2.0
            while time.perf_counter() < acquire_by:
                if killed_shard in replica_b.map.held():
                    failover_seconds = time.perf_counter() - kill_t
                    break
                time.sleep(retry_period or 0.05)
    finally:
        kubelet.stop()
        replica_a.stop()
        replica_b.stop()
        store.stop_watchers()
        n_copies = copies.stop()
        if trace:
            trace_mod.configure(False)

    if kill_shard and killed_shard is not None and failover_seconds is None:
        violations.append(
            f"killed shard {killed_shard} never re-acquired by the "
            "standby replica")

    owned = {i: 0 for i in range(shards)}
    for ns, name, _ in store.keys(store_mod.TPUJOBS):
        snap = store.get_snapshot(store_mod.TPUJOBS, ns, name)
        if snap is not None:
            owned[shard_for(ns, snap.metadata.uid, shards)] += 1
    hits = store.watch_cache_hits - hits0
    misses = store.watch_cache_misses - misses0
    reassignments = replica_a.map.reassignments + replica_b.map.reassignments

    durations_snap = list(durations)
    syncs = len(durations_snap)
    result = {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": syncs,
        "syncs_per_sec": round(syncs / convergence, 1),
        "reconcile_p50_ms": round(
            _percentile(durations_snap, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(
            _percentile(durations_snap, 0.99) * 1e3, 3),
        "deepcopies_per_sync": round(n_copies / max(1, syncs), 1),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "shards": shards,
        "threadiness_per_shard": per_shard_threads,
        "per_shard_jobs_per_sec": {
            str(i): round(owned[i] / convergence, 2)
            for i in range(shards)},
        "shard_reassignments": reassignments,
        "watch_cache_hit_rate": (
            round(hits / (hits + misses), 3) if hits + misses else None),
        "shard_kill": {
            "enabled": bool(kill_shard),
            "killed_shard": killed_shard,
            "failover_seconds": (round(failover_seconds, 3)
                                 if failover_seconds is not None
                                 else None),
        },
        "ownership_violations": list(violations),
        "tracing": trace,
    }
    if trace:
        result["phase_attribution"] = _phase_attribution(
            trace_mod.RECORDER.phase_totals(), convergence)
    return result


def run_sharded_chaos_bench(jobs: int, workers: int, shards: int,
                            threadiness: int, timeout: float,
                            profile_name: str = "default", seed: int = 0,
                            profile=None, kubelet_tick: float = 0.01,
                            crashes: int = 1,
                            resync_period: float = 0.25) -> Dict:
    """Split-brain chaos scenario for the sharded control plane
    (hack/verify-chaos-invariants.py --sharded): two replicas contend
    for N shard leases on the HEALTHY base store while every
    controller reconciles through a seeded ``FaultProfile`` (write/read
    5xx, 409s, timeouts, stale reads, dropped watch events). Mid-run,
    ``crashes`` shard holders are killed the hard way — elector dead
    without releasing the lease, controller state gone — so the
    survivor must wait out the lease and take over through the faults.

    Correctness bar, recorded in the artifact:
      * ``ownership_violations`` empty — every sync ran on the shard
        that owns the job's (namespace, uid) hash, and no shard ever
        had two live controllers (the no-double-reconcile proof).
      * ``invariant_violations`` empty — every crashed shard was
        re-acquired, no orphaned pods, no duplicate live pod
        identities, and the fleet converged.
    Availability cost (failover gaps) is allowed; correctness loss is
    not."""
    from tf_operator_tpu.runtime.chaos import ChaosStore, FaultProfile
    from tf_operator_tpu.runtime.leaderelection import shard_for

    base = Store()
    if profile is None:
        profile = FaultProfile.named(profile_name, seed=seed)
    chaos = ChaosStore(base, profile)
    kubelet = FakeKubelet(base, tick=kubelet_tick)
    durations: List[float] = []
    ownership_violations: List[str] = []
    violations: List[str] = []
    lock = threading.Lock()
    active: Dict[int, str] = {}
    per_shard_threads = max(1, threadiness // shards)
    # Leases live on the healthy base store (a flaky lease CAS is a
    # different failure mode than a flaky API server); the controllers
    # reconcile through the fault injector, with the chaos-bench
    # watchdog pacing so dropped watches unblock in seconds.
    replica_a = _ShardedReplica("replica-a", base, shards,
                                per_shard_threads, durations,
                                ownership_violations, lock, active,
                                controller_store=chaos,
                                expectations_timeout=2.0)
    replica_b = _ShardedReplica("replica-b", base, shards,
                                per_shard_threads, durations,
                                ownership_violations, lock, active,
                                controller_store=chaos,
                                expectations_timeout=2.0)
    replica_a.map.start()
    if not replica_a.map.wait_until_held(shards, timeout=30.0):
        raise TimeoutError(
            f"replica A holds {sorted(replica_a.map.held())} of "
            f"{shards} shards after 30s")
    replica_b.map.start()

    stop_aux = threading.Event()

    def resync() -> None:
        """Production resync backstop, shard-routed: every job is
        re-enqueued on whichever live controller owns its hash — the
        recovery path for dropped watch events."""
        while not stop_aux.wait(resync_period):
            owners: Dict[int, TPUJobController] = {}
            for rep in (replica_a, replica_b):
                for idx, c in list(rep.controllers.items()):
                    owners[idx] = c
            try:
                for ns, name, _ in base.keys(store_mod.TPUJOBS):
                    snap = base.get_snapshot(store_mod.TPUJOBS, ns, name)
                    if snap is None:
                        continue
                    c = owners.get(
                        shard_for(ns, snap.metadata.uid, shards))
                    if c is not None:
                        c.enqueue(f"{ns}/{name}")
            except Exception:
                pass  # racing a takeover; next period retries

    kubelet.start()
    resync_t = threading.Thread(target=resync, daemon=True,
                                name="shard-resync")
    t0 = time.perf_counter()
    # (victim replica name, shard index, crash wall time)
    crashed: List[tuple] = []
    failovers: List[float] = []
    try:
        for i in range(jobs):
            job = testutil.new_tpujob(worker=workers,
                                      name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            base.create(store_mod.TPUJOBS, job)
        resync_t.start()

        deadline = t0 + timeout
        next_kill_at = max(1, jobs // 3)
        while True:
            succeeded = sum(base.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if len(crashed) < crashes and succeeded >= next_kill_at:
                # Kill whoever currently holds the target shard —
                # after the first failover that can be either replica.
                target = (shards - 1 - len(crashed)) % shards
                victim = next(
                    (r for r in (replica_a, replica_b)
                     if target in r.map.held()), None)
                if victim is not None:
                    victim.crash_shard(target)
                    crashed.append(
                        (victim.name, target, time.perf_counter()))
                    next_kill_at = succeeded + max(1, jobs // 4)
            for vname, shard, tk in crashed[len(failovers):]:
                survivor = (replica_b if vname == "replica-a"
                            else replica_a)
                if shard in survivor.map.held():
                    failovers.append(time.perf_counter() - tk)
                else:
                    break
            if succeeded >= jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded after "
                    f"{timeout}s (A holds "
                    f"{sorted(replica_a.map.held())}, B holds "
                    f"{sorted(replica_b.map.held())}, "
                    f"{len(crashed)} shard crash(es))")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
        if len(failovers) < len(crashed):
            # A small fleet can converge before the survivor has waited
            # out the dead leader's lease (1.0s here, and a loaded CI
            # host starves the elector threads well past that) — give
            # each pending takeover the worst-case acquisition window
            # before calling the shard orphaned.
            acquire_by = time.perf_counter() + 3 * 1.0 + 2.0
            while (len(failovers) < len(crashed)
                   and time.perf_counter() < acquire_by):
                for vname, shard, tk in crashed[len(failovers):]:
                    survivor = (replica_b if vname == "replica-a"
                                else replica_a)
                    if shard in survivor.map.held():
                        failovers.append(time.perf_counter() - tk)
                    else:
                        break
                time.sleep(0.05)
    finally:
        stop_aux.set()
        kubelet.stop()
        replica_a.stop()
        replica_b.stop()
        base.stop_watchers()

    for vname, shard, tk in crashed[len(failovers):]:
        survivor = replica_b if vname == "replica-a" else replica_a
        if shard in survivor.map.held():
            failovers.append(time.perf_counter() - tk)
        else:
            violations.append(
                f"shard {shard} crashed on {vname} was never "
                "re-acquired by the surviving replica")

    # ---- post-convergence invariants (on the BASE store) -------------
    live_jobs = {j.metadata.uid: j
                 for j in base.list(store_mod.TPUJOBS,
                                    namespace=NAMESPACE)}
    seen_identity: Dict[tuple, str] = {}
    for p in base.list(store_mod.PODS, namespace=NAMESPACE):
        ref = p.metadata.controller_ref()
        if ref is None or ref.uid not in live_jobs:
            violations.append(
                f"orphaned pod {p.metadata.name}: controller owner "
                "missing from the store")
            continue
        if p.status.phase in ("Succeeded", "Failed"):
            continue
        ident = (ref.uid,
                 p.metadata.labels.get(constants.LABEL_REPLICA_TYPE),
                 p.metadata.labels.get(constants.LABEL_REPLICA_INDEX))
        if ident in seen_identity:
            violations.append(
                f"duplicate live pods for identity {ident}: "
                f"{seen_identity[ident]} and {p.metadata.name}")
        seen_identity[ident] = p.metadata.name

    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": len(durations),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "shards": shards,
        "threadiness_per_shard": per_shard_threads,
        "chaos_profile": profile_name,
        "chaos_seed": seed,
        "faults_injected": chaos.injector.snapshot(),
        "faults_injected_total": chaos.injector.total,
        "shard_crashes": [
            {"replica": v, "shard": s} for v, s, _ in crashed],
        "failover_seconds": [round(f, 3) for f in failovers],
        "shard_reassignments": (replica_a.map.reassignments
                                + replica_b.map.reassignments),
        "ownership_violations": list(ownership_violations),
        "invariant_violations": list(violations),
    }


def run_tenant_bench(tenants: int, jobs_per_tenant: int, workers: int,
                     threadiness: int, timeout: float,
                     chips_per_job: int = 4,
                     kubelet_tick: float = 0.01,
                     stagger: float = 0.2) -> Dict:
    """Multi-tenant contention scenario: ``tenants`` queues over ONE
    cohort, each with nominal quota for exactly one job, all submitting
    ``jobs_per_tenant`` jobs. Tenants 0..N-2 submit at t0 and borrow
    the idle cohort capacity; the LAST tenant submits ``stagger``
    seconds later, so its nominal demand arrives against a fully
    borrowed cohort and must be satisfied by reclaim preemptions.

    Reports per-queue admission wait (job submit -> SliceGroup
    Inqueue) and reclaim counts on top of the run_bench-style
    convergence numbers."""
    from tf_operator_tpu.api.types import (
        ClusterQueue,
        ClusterQueueSpec,
        TenantQueue,
        TenantQueueSpec,
    )
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.controller.quota import TenantQueueManager
    from tf_operator_tpu.runtime import metrics

    store = Store()
    total_chips = tenants * chips_per_job
    quota = TenantQueueManager(store)
    gang = SliceGangScheduler(store, total_chips=total_chips, quota=quota)
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE)
    queues = [f"tenant-{t}" for t in range(tenants)]
    for q in queues:
        cq = ClusterQueue(spec=ClusterQueueSpec(
            nominal_chips=chips_per_job, cohort="bench"))
        cq.metadata.name = f"cq-{q}"
        cq.metadata.namespace = ""
        store.create(store_mod.CLUSTERQUEUES, cq)
        tq = TenantQueue(spec=TenantQueueSpec(cluster_queue=f"cq-{q}"))
        tq.metadata.name = q
        tq.metadata.namespace = NAMESPACE
        store.create(store_mod.TENANTQUEUES, tq)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    timer = _SyncTimer(controller)
    # Borrowers must still be running when the late tenant's demand
    # arrives, or there is nothing to reclaim; the wide margin keeps
    # the reclaim deterministic on slow shared CI.
    kubelet = FakeKubelet(store, tick=kubelet_tick,
                          admitted=group_admitted,
                          min_run_seconds=stagger + 1.0)

    # submit time per job + first-Inqueue time per group, for the
    # per-queue admission-wait numbers (wall clock, one process).
    submit_t: Dict[str, float] = {}
    inqueue_t: Dict[str, float] = {}
    inqueue_lock = threading.Lock()

    def on_group_event(event_type: str, group) -> None:
        if group.status.phase in (PHASE_INQUEUE, PHASE_RUNNING):
            with inqueue_lock:
                inqueue_t.setdefault(group.metadata.name,
                                     time.perf_counter())

    watcher = store.watch(store_mod.SLICEGROUPS, on_group_event)
    reclaims_before = {q: metrics.quota_reclaims.value(queue=q)
                       for q in queues}

    def submit(tenant: int, index: int) -> None:
        q = queues[tenant]
        name = f"bench-{tenant:02d}-{index:03d}"
        job = testutil.new_tpujob(worker=workers, name=name,
                                  namespace=NAMESPACE)
        job.spec.slice.accelerator = f"v5e-{chips_per_job}"
        job.spec.queue_name = q
        submit_t[name] = time.perf_counter()
        store.create(store_mod.TPUJOBS, job)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    total_jobs = tenants * jobs_per_tenant
    try:
        for t in range(tenants - 1):
            for i in range(jobs_per_tenant):
                submit(t, i)
        time.sleep(stagger)  # the late tenant's demand forces reclaim
        for i in range(jobs_per_tenant):
            submit(tenants - 1, i)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= total_jobs:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{total_jobs} jobs Succeeded after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        kubelet.stop()
        controller.stop()
        watcher.stop()
        store.stop_watchers()

    per_queue = {}
    reclaims_total = 0
    for t, q in enumerate(queues):
        waits = []
        for i in range(jobs_per_tenant):
            name = f"bench-{t:02d}-{i:03d}"
            if name in submit_t and name in inqueue_t:
                waits.append(inqueue_t[name] - submit_t[name])
        reclaims = int(metrics.quota_reclaims.value(queue=q)
                       - reclaims_before[q])
        reclaims_total += reclaims
        per_queue[q] = {
            "jobs": jobs_per_tenant,
            "admission_wait_mean_ms": round(
                sum(waits) / len(waits) * 1e3, 3) if waits else None,
            "admission_wait_max_ms": round(
                max(waits) * 1e3, 3) if waits else None,
            "reclaims": reclaims,
        }
    durations = timer.snapshot()
    syncs = len(durations)
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(total_jobs / convergence, 2),
        "syncs": syncs,
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "tenants": tenants,
        "jobs_per_tenant": jobs_per_tenant,
        "jobs": total_jobs,
        "workers_per_job": workers,
        "pods": total_jobs * workers,
        "chips_per_job": chips_per_job,
        "cohort_chips": total_chips,
        "threadiness": threadiness,
        "reclaims_total": reclaims_total,
        "per_queue": per_queue,
    }


class WorkUnitKubelet(threading.Thread):
    """Fake data plane for the elastic/oversubscribe scenario: models a
    DATA-PARALLEL training job whose throughput is proportional to the
    slices it currently holds. Per tick, a gang whose expected worker
    pods are ALL Running advances its job-level progress by its current
    ``spec.slice.numSlices`` work units; pods publish CheckpointRecords
    on the periodic cadence and ack save-before-evict barriers at the
    CURRENT progress (so an acked shrink loses zero committed steps).
    Restore semantics are faithful: a fresh incarnation resumes from
    its rendered ``TPUJOB_RESTORE_STEP`` — uncommitted progress past
    the last save is genuinely lost on a world restart, which is
    exactly the cost the goodput comparison must charge resizes for."""

    def __init__(self, store: Store, work_units: int, admitted=None,
                 tick: float = 0.01, save_interval: int = 20):
        super().__init__(name="workunit-kubelet", daemon=True)
        self.store = store
        self.work_units = work_units
        self.admitted = admitted
        self.tick = tick
        self.save_interval = save_interval
        self.progress: Dict[str, int] = {}       # job name -> work units
        self.min_slices_violations: List[str] = []
        self._acked: Dict[Tuple[str, str, str], str] = {}
        self._last_save: Dict[str, int] = {}
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        from tf_operator_tpu.api.types import (
            CheckpointRecord,
            CheckpointRecordStatus,
        )

        while not self._stop.is_set():
            jobs = {j.metadata.name: j for j in self.store.list(
                store_mod.TPUJOBS, namespace=NAMESPACE)}
            pods_by_job: Dict[str, list] = {}
            for p in self.store.list(store_mod.PODS, namespace=NAMESPACE):
                if p.status.phase in ("Succeeded", "Failed"):
                    continue
                jn = p.metadata.labels.get(constants.LABEL_JOB_NAME, "")
                pods_by_job.setdefault(jn, []).append(p)
            for name, job in jobs.items():
                sl = job.spec.slice
                if (sl.min_slices is not None
                        and sl.num_slices < sl.min_slices):
                    self.min_slices_violations.append(
                        f"job {name}: numSlices {sl.num_slices} < "
                        f"minSlices {sl.min_slices}")
                self._drive(job, pods_by_job.get(name, []),
                            CheckpointRecord, CheckpointRecordStatus)
            self._stop.wait(self.tick)

    def _drive(self, job, pods, record_cls, status_cls) -> None:
        name = job.metadata.name
        expected = sum(s.replicas or 0
                       for s in job.spec.replica_specs.values())
        for p in pods:
            if p.status.phase == PodPhase.PENDING:
                if (self.admitted is not None
                        and not self.admitted(p.metadata.namespace, name)):
                    continue
                self._start(p, name)
        running = [p for p in pods if p.status.phase == PodPhase.RUNNING]
        if name not in self.progress:
            return
        progress = self.progress[name]
        # Barrier acks first, at the CURRENT progress — and no progress
        # is advanced while a notice is outstanding, so the committed
        # step equals the progress the shrink evicts at (zero lost).
        noticed = False
        for p in running:
            notice = p.metadata.annotations.get(
                constants.ANNOTATION_PREEMPT_NOTICE, "")
            if not notice:
                continue
            noticed = True
            key = (p.metadata.namespace, p.metadata.name, p.metadata.uid)
            if self._acked.get(key) != notice:
                barrier = json.loads(notice).get("barrier", "")
                self._publish(p, progress, barrier, record_cls,
                              status_cls)
                self._acked[key] = notice
        if noticed:
            return
        if expected == 0 or len(running) != expected or len(pods) != expected:
            return  # gang not fully up (admission gate or mid-restart)
        progress += job.spec.slice.num_slices
        self.progress[name] = progress
        if (progress - self._last_save.get(name, 0) >= self.save_interval
                or progress >= self.work_units):
            self._last_save[name] = progress
            for p in running:
                self._publish(p, progress, "", record_cls, status_cls)
        if progress >= self.work_units:
            for p in pods:
                patch = Pod(metadata=ObjectMeta(
                    name=p.metadata.name,
                    namespace=p.metadata.namespace))
                patch.status = PodStatus(
                    phase=PodPhase.SUCCEEDED, start_time=testutil.now(),
                    container_statuses=[ContainerStatus(
                        name=constants.DEFAULT_CONTAINER_NAME,
                        state="Terminated", exit_code=0)])
                try:
                    self.store.update_status(store_mod.PODS, patch)
                except (store_mod.NotFoundError, store_mod.ConflictError):
                    pass

    def _start(self, pod, job_name: str) -> None:
        restore = None
        for c in pod.spec.containers:
            if constants.ENV_RESTORE_STEP in c.env:
                restore = int(c.env[constants.ENV_RESTORE_STEP])
        if restore is not None:
            # World restart: the incarnation resumes from the committed
            # step — uncommitted progress past the last save is lost
            # (the honest cost of a resize restart).
            self.progress[job_name] = restore
            self._last_save[job_name] = restore
        else:
            self.progress.setdefault(job_name, 0)
        patch = Pod(metadata=ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace))
        patch.status = PodStatus(phase=PodPhase.RUNNING,
                                 start_time=testutil.now())
        try:
            self.store.update_status(store_mod.PODS, patch)
        except (store_mod.NotFoundError, store_mod.ConflictError):
            pass

    def _publish(self, pod, step: int, barrier: str, record_cls,
                 status_cls) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        status = status_cls(step=step, progress_step=step,
                            barrier_id=barrier, directory="/bench/ckpt",
                            save_seconds=0.001, updated_at=testutil.now())
        try:
            existing = self.store.try_get(store_mod.CHECKPOINTRECORDS,
                                          ns, name)
            if existing is None:
                self.store.create(store_mod.CHECKPOINTRECORDS, record_cls(
                    metadata=ObjectMeta(
                        name=name, namespace=ns,
                        labels=dict(pod.metadata.labels),
                        owner_references=[r.deepcopy() for r in
                                          pod.metadata.owner_references]),
                    status=status))
            else:
                existing.status = status
                self.store.update_status(store_mod.CHECKPOINTRECORDS,
                                         existing)
        except (store_mod.AlreadyExistsError, store_mod.ConflictError,
                store_mod.NotFoundError):
            pass


def _resize_counts() -> Dict[str, float]:
    """Current gang_resizes totals by direction (labels: direction,
    reason)."""
    from tf_operator_tpu.runtime import metrics

    out = {"grow": 0.0, "shrink": 0.0}
    for labels, v in metrics.gang_resizes.collect():
        out[labels[0]] = out.get(labels[0], 0.0) + v
    return out


def _oversubscribe_once(elastic: bool, tenants: int, threadiness: int,
                        timeout: float, chips_per_slice: int,
                        work_units: int, stagger: float,
                        save_interval: int, barrier_timeout: float,
                        kubelet_tick: float) -> Dict:
    """One oversubscribe run: ``tenants`` queues over one cohort, each
    submitting ONE elastic job (minSlices=1, maxSlices=tenants) at
    ``stagger``-second intervals against a cluster that fits exactly
    one slice per tenant. With ``elastic`` on, the resize pass grows
    early arrivals into the idle capacity and shrinks them (zero
    committed steps lost, via the save-before-evict barrier) as later
    tenants' nominal demands arrive; off, every job is pinned at its
    nominal single slice — the static-allocation baseline."""
    from tf_operator_tpu.api.types import (
        CheckpointPolicy,
        ClusterQueue,
        ClusterQueueSpec,
        TenantQueue,
        TenantQueueSpec,
    )
    from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.controller.quota import TenantQueueManager
    from tf_operator_tpu.runtime import metrics

    store = Store()
    total_chips = tenants * chips_per_slice
    quota = TenantQueueManager(store)
    ckpt = CheckpointCoordinator(store).start()
    gang = SliceGangScheduler(store, total_chips=total_chips,
                              quota=quota, ckpt=ckpt, elastic=elastic)
    ckpt.on_ack = gang.readmit
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE, ckpt=ckpt)
    for t in range(tenants):
        cq = ClusterQueue(spec=ClusterQueueSpec(
            nominal_chips=chips_per_slice, cohort="bench"))
        cq.metadata.name = f"cq-tenant-{t}"
        cq.metadata.namespace = ""
        store.create(store_mod.CLUSTERQUEUES, cq)
        tq = TenantQueue(spec=TenantQueueSpec(
            cluster_queue=f"cq-tenant-{t}"))
        tq.metadata.name = f"tenant-{t}"
        tq.metadata.namespace = NAMESPACE
        store.create(store_mod.TENANTQUEUES, tq)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    kubelet = WorkUnitKubelet(store, work_units=work_units,
                              admitted=group_admitted, tick=kubelet_tick,
                              save_interval=save_interval)
    resizes_before = _resize_counts()
    acked_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="acked")
    timeout_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="timeout")
    lost_before = metrics.steps_lost_per_disruption.sum_value(
        job_namespace=NAMESPACE)

    stop_resync = threading.Event()

    def resync() -> None:
        # Steady-state grows have no store event to ride (nothing
        # changes until the resize pass itself acts): the production
        # resync loop is what re-drives admission, so the bench runs
        # one too.
        while not stop_resync.wait(0.05):
            try:
                for key in store.project(store_mod.TPUJOBS,
                                         lambda j: j.key(),
                                         namespace=NAMESPACE):
                    controller.enqueue(key)
            except Exception:
                pass

    resync_thread = threading.Thread(target=resync, name="resync",
                                     daemon=True)
    controller.run(threadiness=threadiness)
    kubelet.start()
    resync_thread.start()
    t0 = time.perf_counter()
    try:
        for t in range(tenants):
            if t > 0:
                time.sleep(stagger)
            job = testutil.new_tpujob(worker=1, name=f"bench-os-{t}",
                                      namespace=NAMESPACE)
            job.spec.slice.accelerator = f"v5e-{chips_per_slice}"
            job.spec.slice.num_slices = 1
            if elastic:
                job.spec.slice.min_slices = 1
                job.spec.slice.max_slices = tenants
            job.spec.queue_name = f"tenant-{t}"
            job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
                enabled=True, directory="/bench/ckpt",
                interval_steps=save_interval,
                barrier_timeout_seconds=barrier_timeout)
            store.create(store_mod.TPUJOBS, job)

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= tenants:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{tenants} jobs Succeeded after "
                    f"{timeout}s (elastic={elastic})")
            time.sleep(0.02)
        makespan = time.perf_counter() - t0
    finally:
        stop_resync.set()
        kubelet.stop()
        controller.stop()
        ckpt.stop()
        store.stop_watchers()

    resizes_after = _resize_counts()
    total_work = tenants * work_units
    return {
        "elastic": elastic,
        "makespan_seconds": round(makespan, 3),
        "goodput_units_per_sec": round(total_work / makespan, 2),
        "resizes_grow": int(resizes_after["grow"]
                            - resizes_before["grow"]),
        "resizes_shrink": int(resizes_after["shrink"]
                              - resizes_before["shrink"]),
        "barriers_acked": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="acked") - acked_before),
        "barriers_timeout": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="timeout")
            - timeout_before),
        "steps_lost_total": int(
            metrics.steps_lost_per_disruption.sum_value(
                job_namespace=NAMESPACE) - lost_before),
        "min_slices_violations": list(kubelet.min_slices_violations[:8]),
    }


def run_oversubscribe_bench(tenants: int, threadiness: int,
                            timeout: float, chips_per_slice: int = 4,
                            work_units: int = 480, stagger: float = 1.0,
                            save_interval: int = 10,
                            barrier_timeout: float = 10.0,
                            kubelet_tick: float = 0.01) -> Dict:
    """Oversubscribe scenario (ROADMAP item 2 acceptance): N tenants
    over-subscribe a cluster that holds exactly one nominal slice per
    tenant; the SAME staggered submission schedule is run twice — with
    the elastic resize pass on, and pinned at static nominal
    allocation — and aggregate goodput (work units completed per wall
    second) is compared. Elastic must win by riding idle capacity early
    and degrading (shrink, keep training) instead of idling when
    reclaim pressure arrives."""
    static = _oversubscribe_once(
        False, tenants, threadiness, timeout, chips_per_slice,
        work_units, stagger, save_interval, barrier_timeout,
        kubelet_tick)
    elastic = _oversubscribe_once(
        True, tenants, threadiness, timeout, chips_per_slice,
        work_units, stagger, save_interval, barrier_timeout,
        kubelet_tick)
    gain = (elastic["goodput_units_per_sec"]
            / max(1e-9, static["goodput_units_per_sec"]) - 1.0) * 100.0
    return {
        "tenants": tenants,
        "jobs": tenants,
        "chips_per_slice": chips_per_slice,
        "cluster_chips": tenants * chips_per_slice,
        "max_slices": tenants,
        "work_units_per_job": work_units,
        "stagger_seconds": stagger,
        "save_interval_steps": save_interval,
        "threadiness": threadiness,
        "goodput_gain_pct": round(gain, 2),
        "elastic": elastic,
        "static": static,
        "invariant_violations": list(elastic["min_slices_violations"])
        + list(static["min_slices_violations"]),
    }


class CkptFakeKubelet(FakeKubelet):
    """FakeKubelet that also plays the checkpointing WORKER + node agent
    (the data-plane relay the local backend provides in production):
    Running pods advance one training step per tick, publish periodic
    saves and barrier acks as CheckpointRecords, and a recreated pod
    resumes from the TPUJOB_RESTORE_STEP env the controller rendered —
    so the disruption scenario measures the full save-before-evict /
    restore-with-identity loop with no subprocess in it."""

    def __init__(self, store: Store, steps: int, tick: float = 0.01,
                 admitted=None, save_interval: int = 20):
        super().__init__(store, tick=tick, admitted=admitted)
        self.steps = steps
        self.save_interval = save_interval
        # (ns, pod) -> training progress of the CURRENT incarnation
        # (keyed by uid so a recreate re-reads its restore env).
        self._progress: Dict[Tuple[str, str, str], int] = {}
        self._acked: Dict[Tuple[str, str, str], str] = {}

    def run(self) -> None:  # overrides FakeKubelet.run
        from tf_operator_tpu.api.types import (
            CheckpointRecord,
            CheckpointRecordStatus,
        )

        while not self._stop.is_set():
            pods = self.store.list(store_mod.PODS, namespace=NAMESPACE)
            for pod in pods:
                if pod.status.phase == PodPhase.PENDING:
                    job_name = pod.metadata.labels.get(
                        constants.LABEL_JOB_NAME, "")
                    if (self.admitted is not None
                            and not self.admitted(pod.metadata.namespace,
                                                  job_name)):
                        continue
                    self._start(pod)
                elif pod.status.phase == PodPhase.RUNNING:
                    self._step(pod, CheckpointRecord,
                               CheckpointRecordStatus)
            self._stop.wait(self.tick)

    def _key(self, pod) -> Tuple[str, str, str]:
        return (pod.metadata.namespace, pod.metadata.name,
                pod.metadata.uid)

    def _start(self, pod) -> None:
        restore = 0
        for c in pod.spec.containers:
            if constants.ENV_RESTORE_STEP in c.env:
                restore = int(c.env[constants.ENV_RESTORE_STEP])
        self._progress[self._key(pod)] = restore
        patch = Pod(metadata=ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace))
        patch.status = PodStatus(phase=PodPhase.RUNNING,
                                 start_time=testutil.now())
        try:
            self.store.update_status(store_mod.PODS, patch)
        except (store_mod.NotFoundError, store_mod.ConflictError):
            pass

    def _step(self, pod, record_cls, status_cls) -> None:
        key = self._key(pod)
        if key not in self._progress:
            self._start(pod)  # Running before we saw it Pending
            return
        self._progress[key] += 1
        progress = self._progress[key]
        notice = pod.metadata.annotations.get(
            constants.ANNOTATION_PREEMPT_NOTICE, "")
        barrier = ""
        if notice and self._acked.get(key) != notice:
            barrier = json.loads(notice).get("barrier", "")
        periodic = progress % self.save_interval == 0
        if barrier or periodic or progress >= self.steps:
            self._publish(pod, progress, barrier, record_cls, status_cls)
            if barrier:
                self._acked[key] = notice
        if progress >= self.steps:
            patch = Pod(metadata=ObjectMeta(
                name=pod.metadata.name,
                namespace=pod.metadata.namespace))
            patch.status = PodStatus(
                phase=PodPhase.SUCCEEDED, start_time=testutil.now(),
                container_statuses=[ContainerStatus(
                    name=constants.DEFAULT_CONTAINER_NAME,
                    state="Terminated", exit_code=0)])
            try:
                self.store.update_status(store_mod.PODS, patch)
            except (store_mod.NotFoundError, store_mod.ConflictError):
                pass

    def _publish(self, pod, progress: int, barrier: str,
                 record_cls, status_cls) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        status = status_cls(step=progress, progress_step=progress,
                            barrier_id=barrier, directory="/bench/ckpt",
                            save_seconds=0.001,
                            updated_at=testutil.now())
        try:
            existing = self.store.try_get(store_mod.CHECKPOINTRECORDS,
                                          ns, name)
            if existing is None:
                self.store.create(store_mod.CHECKPOINTRECORDS, record_cls(
                    metadata=ObjectMeta(
                        name=name, namespace=ns,
                        labels={k: v
                                for k, v in pod.metadata.labels.items()},
                        owner_references=[r.deepcopy() for r in
                                          pod.metadata.owner_references]),
                    status=status))
            else:
                existing.status = status
                self.store.update_status(store_mod.CHECKPOINTRECORDS,
                                         existing)
        except (store_mod.AlreadyExistsError, store_mod.ConflictError,
                store_mod.NotFoundError):
            pass  # raced; next periodic publish lands


def run_disruption_bench(jobs: int, workers: int, threadiness: int,
                         timeout: float, disruptions: int,
                         steps: int = 80, save_interval: int = 20,
                         chips_per_job: int = 4,
                         barrier_timeout: float = 10.0,
                         kubelet_tick: float = 0.01) -> Dict:
    """Disruption/goodput scenario: checkpointing fake jobs under
    injected drains. Each disruption takes the slice-health path —
    ``ready_to_evict`` (opens the save-before-evict barrier), evict the
    gang's pods once it answers True, ``gang.displace`` — against a live
    CheckpointCoordinator; the rebound pods restore from the
    barrier-committed step. Reports barrier outcomes, steps lost, and
    the goodput ratio on top of the convergence numbers."""
    from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.api.types import CheckpointPolicy
    from tf_operator_tpu.runtime import metrics

    store = Store()
    ckpt = CheckpointCoordinator(store).start()
    gang = SliceGangScheduler(store, total_chips=None, ckpt=ckpt)
    ckpt.on_ack = gang.readmit
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE, ckpt=ckpt)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    timer = _SyncTimer(controller)
    kubelet = CkptFakeKubelet(store, steps=steps, tick=kubelet_tick,
                              admitted=group_admitted,
                              save_interval=save_interval)

    acked_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="acked")
    timeout_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="timeout")
    lost_sum_before = metrics.steps_lost_per_disruption.sum_value(
        job_namespace=NAMESPACE)
    lost_n_before = metrics.steps_lost_per_disruption.count_value(
        job_namespace=NAMESPACE)

    injected = [0]
    disruptor_stop = threading.Event()

    def disrupt() -> None:
        """One disruption at a time, round-robin over live gangs: open
        the barrier, then evict + displace the moment it completes —
        the slice-health drain path, level-triggered just like it."""
        cursor = 0
        in_flight: Optional[str] = None
        while not disruptor_stop.is_set() and injected[0] < disruptions:
            target = in_flight
            if target is None:
                live = sorted(
                    g.metadata.name
                    for g in store.list(store_mod.SLICEGROUPS,
                                        namespace=NAMESPACE)
                    if g.status.phase in (PHASE_INQUEUE, PHASE_RUNNING)
                    and not g.status.displaced_reason)
                if not live:
                    disruptor_stop.wait(kubelet_tick)
                    continue
                target = live[cursor % len(live)]
                cursor += 1
            if ckpt.ready_to_evict(NAMESPACE, target,
                                   "bench disruption"):
                for p in store.list(store_mod.PODS, namespace=NAMESPACE,
                                    selector={constants.LABEL_JOB_NAME:
                                              target}):
                    if p.status.phase not in ("Succeeded", "Failed"):
                        store.try_delete(store_mod.PODS, NAMESPACE,
                                         p.metadata.name)
                gang.displace(NAMESPACE, target, "bench disruption")
                injected[0] += 1
                in_flight = None
            else:
                in_flight = target  # barrier open; re-consult next tick
            disruptor_stop.wait(kubelet_tick)

    disruptor = threading.Thread(target=disrupt, name="disruptor",
                                 daemon=True)

    controller.run(threadiness=threadiness)
    kubelet.start()
    t0 = time.perf_counter()
    try:
        for i in range(jobs):
            # The goodput gauge is process-global and keyed by job
            # name: zero any residue from an earlier scenario sharing
            # the bench namespace (the >0.0 filter below drops zeros).
            metrics.job_goodput_ratio.set(0.0, job_namespace=NAMESPACE,
                                          job=f"bench-{i:04d}")
            job = testutil.new_tpujob(worker=workers,
                                      name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            job.spec.slice.accelerator = f"v5e-{chips_per_job}"
            job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
                enabled=True, directory="/bench/ckpt",
                interval_steps=save_interval,
                barrier_timeout_seconds=barrier_timeout)
            store.create(store_mod.TPUJOBS, job)
        disruptor.start()

        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= jobs and injected[0] >= disruptions:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded, "
                    f"{injected[0]}/{disruptions} disruptions after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        disruptor_stop.set()
        kubelet.stop()
        controller.stop()
        ckpt.stop()
        store.stop_watchers()

    goodputs = [metrics.job_goodput_ratio.value(
        job_namespace=NAMESPACE, job=f"bench-{i:04d}")
        for i in range(jobs)]
    goodputs = [g for g in goodputs if g > 0.0]
    lost_total = (metrics.steps_lost_per_disruption.sum_value(
        job_namespace=NAMESPACE) - lost_sum_before)
    lost_n = (metrics.steps_lost_per_disruption.count_value(
        job_namespace=NAMESPACE) - lost_n_before)
    restored = [r.status.restored_from_step
                for r in store.list(store_mod.CHECKPOINTRECORDS,
                                    namespace=NAMESPACE)
                if r.status.restored_from_step is not None]
    durations = timer.snapshot()
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": len(durations),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "steps_per_job": steps,
        "save_interval_steps": save_interval,
        "disruptions": disruptions,
        "disruptions_injected": injected[0],
        "barriers_acked": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="acked") - acked_before),
        "barriers_timeout": int(metrics.checkpoint_barriers.value(
            job_namespace=NAMESPACE, outcome="timeout")
            - timeout_before),
        "steps_lost_total": int(lost_total),
        "steps_lost_per_disruption_mean": round(
            lost_total / lost_n, 2) if lost_n else 0.0,
        "goodput_ratio_mean": round(
            sum(goodputs) / len(goodputs), 4) if goodputs else None,
        "goodput_ratio_min": round(min(goodputs), 4) if goodputs else None,
        "restores_observed": len(restored),
    }


def run_chaos_bench(jobs: int, workers: int, threadiness: int,
                    timeout: float, profile_name: str = "default",
                    seed: int = 0, disruptions: int = 2,
                    steps: int = 60, save_interval: int = 15,
                    chips_per_job: int = 4,
                    barrier_timeout: float = 10.0,
                    capacity_fraction: float = 0.6,
                    kubelet_tick: float = 0.01,
                    crash_restarts: int = 1,
                    resync_period: float = 0.5,
                    profile=None,
                    elastic: bool = False,
                    rl: bool = False,
                    actors: int = 2) -> Dict:
    """Chaos scenario: the FULL control plane (gang admission +
    checkpoint barriers + disruptions) reconciling through a seeded
    ``FaultProfile`` (runtime/chaos.py) injected between the operator
    and its store — write/read 5xx, 409 conflicts, timeouts, stale
    reads, dropped watch events — plus ``crash_restarts`` operator
    crash-restarts mid-run (all in-memory state lost, store survives).

    Convergence itself is the headline; the artifact additionally
    records the faults injected, in-place retry totals, degraded-mode
    entries, and the post-convergence INVARIANT CHECKS (orphans,
    duplicate admissions / capacity breaches, unresolved barriers,
    committed-step regressions) — ``invariant_violations`` must be
    empty for the run to count.

    ``elastic=True`` additionally turns the resize pass on: jobs
    declare minSlices=1/maxSlices=2, a spare slice of budget lets the
    grow pass fire, and a resize exerciser requests barrier-gated
    shrinks through the faults — with three extra invariants sampled
    mid-resize: never below minSlices, admitted chips never above the
    budget at the per-group CURRENT size, and every shrink barrier
    resolving acked|timeout.

    ``rl=True`` switches to the heterogeneous-gang rounds
    (hack/verify-chaos-invariants.py --rl): every job carries
    ``actors`` explicit evict-class CPU-only actor replicas next to its
    barrier-class learners, and the disruptor is an actor KILL STORM —
    ``disruptions`` rounds, each deleting at least half of one job's
    live actor pool, with no barrier and no displacement. Two extra
    invariants are sampled throughout: a learner (world-member) pod's
    uid never changes while its job runs — actor-only churn must never
    restart the learner world — and the committed step never regresses
    (docs/rl.md)."""
    from tf_operator_tpu.api.types import CheckpointPolicy
    from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.runtime import metrics
    from tf_operator_tpu.runtime.chaos import (
        ChaosStore,
        FaultProfile,
        crash_controller,
    )
    from tf_operator_tpu.runtime.retry import ControlPlaneHealth

    base = Store()
    if profile is None:
        # An explicit FaultProfile (hack/verify-chaos-invariants.py
        # randomizes one per seed) wins over the named preset.
        profile = FaultProfile.named(profile_name, seed=seed)
    chaos = ChaosStore(base, profile)
    # Capacity below aggregate demand forces real queueing, so the
    # duplicate-admission/capacity invariant is load-bearing, not
    # vacuous. Chips free as jobs finish (slicegroup deleted). Elastic
    # runs instead get ONE spare slice of headroom: every gang admits
    # and the grow pass has exactly one slice to fight over, so
    # resizes churn while the budget invariant still bites.
    if elastic:
        total_chips = (jobs + 1) * chips_per_job
    else:
        total_chips = max(chips_per_job,
                          int(jobs * chips_per_job * capacity_fraction))

    holder: Dict[str, object] = {}
    dur_acc: List[float] = []  # sync durations across crash-restarts

    def build():
        """(Re)build the operator assembly against the surviving
        store — the cold-start path a crash-restart exercises."""
        if "timer" in holder:
            dur_acc.extend(holder["timer"].snapshot())
        cp_health = ControlPlaneHealth(threshold_seconds=1.0)
        ckpt = CheckpointCoordinator(chaos).start()
        gang = SliceGangScheduler(chaos, total_chips=total_chips,
                                  ckpt=ckpt, cp_health=cp_health,
                                  elastic=elastic)
        ckpt.on_ack = gang.readmit
        controller = TPUJobController(
            chaos, config=EngineConfig(enable_gang_scheduling=True),
            gang=gang, namespace=NAMESPACE, ckpt=ckpt,
            cp_health=cp_health)
        # Bench-proportionate expectations watchdog: dropped watch
        # events must unblock in seconds, not the production 5 minutes.
        controller.expectations._timeout = 2.0
        holder.update(controller=controller, gang=gang, ckpt=ckpt,
                      timer=_SyncTimer(controller))
        controller.run(threadiness=threadiness)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = base.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    # Committed-step watermark per job (highest committed step observed
    # at any displace) vs the steps each recreated incarnation restores
    # from. The restore env is rendered at pod-CREATE time from the
    # records at that instant, and the engine races the displacement
    # (a pod recreated between the eviction's deletes and the displace
    # landing sees the committed step of that moment), so a restore may
    # legitimately trail the watermark by the in-flight barrier-ack
    # spread — bounded by one save granule; the worker merely
    # re-executes those steps, the durable checkpoint is untouched
    # (found by verify-chaos-invariants seed 1004; docs/robustness.md
    # "restore-step staleness"). What restart-with-identity must NEVER
    # do once a gang checkpoint is committed: restore from scratch, or
    # regress past a whole save granule.
    # job -> (committed step, wall time the displace recorded it).
    # Only incarnations CREATED after the stamp are judged: the engine
    # recreates pods in the window between an eviction's deletes and
    # the displace landing (their env predates the watermark — the
    # seed-1004 render race), and the kubelet's tick may process a
    # pod object listed before the deletion (seed-1020 TOCTOU) — both
    # are pre-watermark incarnations, not lost steps.
    watermark: Dict[str, tuple] = {}
    violations: List[str] = []

    class _ChaosKubelet(CkptFakeKubelet):
        def _start(self, pod) -> None:
            restore = None
            for c in pod.spec.containers:
                if constants.ENV_RESTORE_STEP in c.env:
                    restore = int(c.env[constants.ENV_RESTORE_STEP])
            job_name = pod.metadata.labels.get(
                constants.LABEL_JOB_NAME, "")
            if restore is None:
                # Production semantics (train/checkpoint.py
                # restore_step): no TPUJOB_RESTORE_STEP rendered means
                # fall back to the NEWEST LOCAL CHECKPOINT, not a cold
                # start. A pod whose env was rendered before the first
                # commit but created after it (the in-place create
                # retries widen that window — verify-chaos seed 1015)
                # therefore still resumes from disk; the records are
                # this harness's disk proxy.
                steps = [r.status.step for r in base.list(
                    store_mod.CHECKPOINTRECORDS, namespace=NAMESPACE,
                    selector={constants.LABEL_JOB_NAME: job_name})
                    if r.status.step >= 0]
                restore = min(steps) if steps else 0
                for c in pod.spec.containers:
                    c.env[constants.ENV_RESTORE_STEP] = str(restore)
            want = watermark.get(job_name)
            created = pod.metadata.creation_timestamp
            if (want is not None and created is not None
                    and created.timestamp() > want[1]
                    and (restore == 0
                         or restore < want[0] - save_interval)):
                violations.append(
                    f"pod {pod.metadata.name} restored from step "
                    f"{restore} with committed watermark {want[0]} "
                    "(committed steps lost across restart)")
            super()._start(pod)

        def _publish(self, pod, progress, barrier, record_cls,
                     status_cls) -> None:
            # RL actors checkpoint nothing (docs/rl.md): an actor
            # record would drag committed_step — the min over records —
            # down to actor pace and poison every learner restore.
            if (pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE)
                    == "actor"):
                return
            super()._publish(pod, progress, barrier, record_cls,
                             status_cls)

    kubelet = _ChaosKubelet(base, steps=steps, tick=kubelet_tick,
                            admitted=group_admitted,
                            save_interval=save_interval)

    injected = [0]
    stop_aux = threading.Event()
    max_admitted = [0]
    shrinks_landed = [0]
    # Bounded shrink exerciser: unbounded shrink/grow churn could eat
    # a pod's uncommitted progress faster than it accrues (a grow
    # restart legitimately rolls back to the committed step), stalling
    # convergence — real clusters pace resizes off real pressure.
    resize_budget = [max(2, disruptions)] if elastic else [0]

    def exercise_resizes() -> None:
        """Request barrier-gated shrinks of grown gangs through the
        fault-injecting store; the grow pass refills them. Stops after
        the budget so convergence stays reachable."""
        while not stop_aux.is_set() and resize_budget[0] > 0:
            gang = holder["gang"]
            try:
                target = None
                for j in base.list(store_mod.TPUJOBS, namespace=NAMESPACE):
                    sl = j.spec.slice
                    if (sl.min_slices is not None
                            and sl.num_slices > sl.min_slices
                            and not cond.is_finished(j.status)):
                        target = j.metadata.name
                        break
                if target is None:
                    stop_aux.wait(kubelet_tick)
                    continue
                if gang.try_shrink(NAMESPACE, target, 1, "chaos",
                                   "chaos shrink"):
                    shrinks_landed[0] += 1
                    resize_budget[0] -= 1
            except Exception:
                pass  # injected fault; retry next tick
            stop_aux.wait(kubelet_tick)

    storms = [0]
    actor_kills = [0]
    learner_uids: Dict[tuple, str] = {}
    committed_seen: Dict[str, int] = {}

    def actor_storm() -> None:
        """The rl-round disruptor: round-robin over live jobs, each
        storm deleting at least half the target's live actor pods in
        one burst — no barrier, no displacement (evict-class
        semantics). The engine recreates the pool; the learner world
        must never notice."""
        from tf_operator_tpu.runtime import metrics as metrics_mod

        cursor = 0
        half = max(1, (actors + 1) // 2)
        while not stop_aux.is_set() and storms[0] < disruptions:
            try:
                live = sorted(
                    j.metadata.name
                    for j in base.list(store_mod.TPUJOBS,
                                       namespace=NAMESPACE)
                    if not cond.is_finished(j.status))
                if not live:
                    stop_aux.wait(kubelet_tick)
                    continue
                target = live[cursor % len(live)]
                cursor += 1
                pool = sorted(
                    (p for p in base.list(
                        store_mod.PODS, namespace=NAMESPACE,
                        selector={constants.LABEL_JOB_NAME: target})
                     if p.metadata.labels.get(
                         constants.LABEL_REPLICA_TYPE) == "actor"
                     and p.status.phase not in ("Succeeded", "Failed")),
                    key=lambda p: p.metadata.name)
                if len(pool) < half:
                    stop_aux.wait(kubelet_tick)
                    continue  # pool not (re)grown yet; storm a whole one
                for p in pool[:half]:
                    if base.try_delete(store_mod.PODS, NAMESPACE,
                                       p.metadata.name):
                        actor_kills[0] += 1
                        metrics_mod.actor_preemptions.inc(
                            job_namespace=NAMESPACE, reason="chaos")
                storms[0] += 1
            except Exception:
                pass  # racing convergence; retry next tick
            stop_aux.wait(kubelet_tick)

    def sample_rl() -> None:
        """The rl-round invariants, sampled against the BASE store:
        (1) a learner (non-actor) pod's uid never changes while its job
        runs — actor-only churn restarting the learner world is THE
        regression this mode exists to catch; (2) the committed step
        (min over the job's CheckpointRecords) never regresses."""
        while not stop_aux.wait(0.05):
            finished = {j.metadata.name
                        for j in base.list(store_mod.TPUJOBS,
                                           namespace=NAMESPACE)
                        if cond.is_finished(j.status)}
            for p in base.list(store_mod.PODS, namespace=NAMESPACE):
                if p.status.phase != "Running":
                    continue
                labels = p.metadata.labels
                jn = labels.get(constants.LABEL_JOB_NAME, "")
                rt = labels.get(constants.LABEL_REPLICA_TYPE, "")
                if jn in finished or rt == "actor":
                    continue
                ident = (jn, rt,
                         labels.get(constants.LABEL_REPLICA_INDEX, ""))
                prev = learner_uids.get(ident)
                if prev is None:
                    learner_uids[ident] = p.metadata.uid
                elif prev != p.metadata.uid:
                    learner_uids[ident] = p.metadata.uid
                    violations.append(
                        f"learner pod {ident} restarted (uid changed) "
                        "during actor-only chaos")
            steps_by_job: Dict[str, List[int]] = {}
            for r in base.list(store_mod.CHECKPOINTRECORDS,
                               namespace=NAMESPACE):
                jn = r.metadata.labels.get(constants.LABEL_JOB_NAME, "")
                if r.status.step >= 0 and jn not in finished:
                    steps_by_job.setdefault(jn, []).append(r.status.step)
            for jn, ss in steps_by_job.items():
                committed = min(ss)
                prev = committed_seen.get(jn)
                if prev is not None and committed < prev:
                    violations.append(
                        f"job {jn} committed step regressed {prev} -> "
                        f"{committed} under actor-only chaos")
                committed_seen[jn] = max(prev or 0, committed)

    def disrupt() -> None:
        """Round-robin planned disruptions through the (current)
        coordinator + gang — every call may hit an injected fault;
        level-triggered retry is the contract."""
        cursor = 0
        in_flight: Optional[str] = None
        while not stop_aux.is_set() and injected[0] < disruptions:
            ckpt = holder["ckpt"]
            gang = holder["gang"]
            try:
                target = in_flight
                if target is None:
                    live = sorted(
                        g.metadata.name
                        for g in base.list(store_mod.SLICEGROUPS,
                                           namespace=NAMESPACE)
                        if g.status.phase in (PHASE_INQUEUE,
                                              PHASE_RUNNING)
                        and not g.status.displaced_reason)
                    if not live:
                        stop_aux.wait(kubelet_tick)
                        continue
                    target = live[cursor % len(live)]
                    cursor += 1
                if ckpt.ready_to_evict(NAMESPACE, target,
                                       "chaos disruption"):
                    committed = ckpt.committed_step(NAMESPACE, target)
                    for p in base.list(
                            store_mod.PODS, namespace=NAMESPACE,
                            selector={constants.LABEL_JOB_NAME: target}):
                        if p.status.phase not in ("Succeeded", "Failed"):
                            base.try_delete(store_mod.PODS, NAMESPACE,
                                            p.metadata.name)
                    if gang.displace(NAMESPACE, target,
                                     "chaos disruption"):
                        if committed is not None:
                            prev = watermark.get(target, (0, 0.0))
                            watermark[target] = (
                                max(prev[0], committed), time.time())
                        injected[0] += 1
                        in_flight = None
                    else:
                        in_flight = target
                else:
                    in_flight = target
            except Exception:
                pass  # injected fault; retry next tick
            stop_aux.wait(kubelet_tick)

    def resync() -> None:
        """The production resync loop (cli.py _resync_loop analog):
        the backstop that makes dropped watch events recoverable."""
        while not stop_aux.wait(resync_period):
            controller = holder.get("controller")
            if controller is None:
                continue
            try:
                for key in base.project(store_mod.TPUJOBS,
                                        lambda j: j.key(),
                                        namespace=NAMESPACE):
                    controller.enqueue(key)
            except Exception:
                pass

    def sample_admission() -> None:
        """Duplicate-admission probe: the chips admitted concurrently
        must never exceed the budget — at each group's CURRENT size,
        so the invariant stays load-bearing mid-resize. Also samples
        the never-below-minSlices floor on every job spec."""
        from tf_operator_tpu.controller.gang import _chips_for

        floor_broken: set = set()
        while not stop_aux.wait(0.05):
            used = sum(base.project(
                store_mod.SLICEGROUPS,
                lambda g: (_chips_for(g)
                           if g.status.phase in (PHASE_INQUEUE,
                                                 PHASE_RUNNING)
                           else None)))
            max_admitted[0] = max(max_admitted[0], used)
            if not elastic:
                continue
            for name, cur, mn in base.project(
                    store_mod.TPUJOBS,
                    lambda j: (j.metadata.name, j.spec.slice.num_slices,
                               j.spec.slice.min_slices),
                    namespace=NAMESPACE):
                if (mn is not None and cur < mn
                        and name not in floor_broken):
                    floor_broken.add(name)
                    violations.append(
                        f"job {name} resized to {cur} slice(s), below "
                        f"minSlices {mn}")

    acked_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="acked")
    timeout_before = metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="timeout")
    retries_before = sum(v for _, v in metrics.api_retries.collect())
    degraded_before = sum(v for _, v in
                          metrics.degraded_entries.collect()) or 0.0

    build()
    kubelet.start()
    aux_specs = [(actor_storm if rl else disrupt, "disruptor"),
                 (resync, "resync"),
                 (sample_admission, "admission-probe")]
    if elastic:
        aux_specs.append((exercise_resizes, "resize-exerciser"))
    if rl:
        aux_specs.append((sample_rl, "rl-probe"))
    aux = [threading.Thread(target=fn, daemon=True, name=name)
           for fn, name in aux_specs]
    t0 = time.perf_counter()
    crashes_done = 0
    try:
        for i in range(jobs):
            # Elastic jobs couple the worker count to the slice count
            # (one host per v5e-4 slice), so the resize pass scales
            # both; the non-elastic shape keeps the historical
            # `workers` fan-out.
            job = testutil.new_tpujob(worker=1 if elastic else workers,
                                      actor=actors if rl else 0,
                                      name=f"bench-{i:04d}",
                                      namespace=NAMESPACE)
            if rl:
                from tf_operator_tpu.api.types import (
                    DisruptionClass,
                    ReplicaType,
                    RolePolicy,
                )

                job.spec.replica_specs[ReplicaType.ACTOR].role_policy = \
                    RolePolicy(chip_consuming=False, preemptible=True,
                               min_replicas=1, max_replicas=actors,
                               disruption_class=DisruptionClass.EVICT)
            job.spec.slice.accelerator = f"v5e-{chips_per_job}"
            if elastic:
                job.spec.slice.min_slices = 1
                job.spec.slice.max_slices = 2
            job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
                enabled=True, directory="/bench/ckpt",
                interval_steps=save_interval,
                barrier_timeout_seconds=barrier_timeout)
            base.create(store_mod.TPUJOBS, job)
        for t in aux:
            t.start()

        deadline = t0 + timeout
        while True:
            succeeded = sum(base.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if (crashes_done < crash_restarts
                    and succeeded >= max(1, jobs // 3)):
                # Operator crash-restart mid-reconcile: kill the whole
                # assembly (workqueue backlog, expectations, barrier
                # deadlines — gone), cold-start a fresh one against the
                # surviving store.
                crash_controller(holder["controller"], holder["ckpt"])
                crashes_done += 1
                build()
            if succeeded >= jobs:
                # Converged. Disruptions are best-effort past this
                # point: once every job finished there is no live gang
                # left to displace, so waiting for the remaining count
                # would hang forever (verify-chaos seed 1023) — the
                # artifact reports how many actually landed.
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"{succeeded}/{jobs} jobs Succeeded, "
                    f"{injected[0]}/{disruptions} disruptions after "
                    f"{timeout}s")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        stop_aux.set()
        kubelet.stop()
        crash_controller(holder.get("controller"), holder.get("ckpt"))
        base.stop_watchers()

    # ---- post-convergence invariants (on the BASE store) -------------
    live_jobs = {}
    for j in base.list(store_mod.TPUJOBS, namespace=NAMESPACE):
        live_jobs[j.metadata.uid] = j
    seen_identity: Dict[tuple, str] = {}
    for p in base.list(store_mod.PODS, namespace=NAMESPACE):
        ref = p.metadata.controller_ref()
        if ref is None or ref.uid not in live_jobs:
            violations.append(
                f"orphaned pod {p.metadata.name}: controller owner "
                "missing from the store")
            continue
        if p.status.phase in ("Succeeded", "Failed"):
            continue
        ident = (ref.uid,
                 p.metadata.labels.get(constants.LABEL_REPLICA_TYPE),
                 p.metadata.labels.get(constants.LABEL_REPLICA_INDEX))
        if ident in seen_identity:
            violations.append(
                f"duplicate live pods for identity {ident}: "
                f"{seen_identity[ident]} and {p.metadata.name}")
        seen_identity[ident] = p.metadata.name
    if max_admitted[0] > total_chips:
        violations.append(
            f"admitted chips peaked at {max_admitted[0]} > budget "
            f"{total_chips} (duplicate admission / double-booking)")
    barriers_acked = int(metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="acked") - acked_before)
    barriers_timeout = int(metrics.checkpoint_barriers.value(
        job_namespace=NAMESPACE, outcome="timeout") - timeout_before)
    if barriers_acked + barriers_timeout < injected[0] + shrinks_landed[0]:
        violations.append(
            f"{injected[0]} disruptions displaced + {shrinks_landed[0]} "
            f"shrinks landed but only "
            f"{barriers_acked + barriers_timeout} barriers resolved "
            "(a barrier was left unresolved)")
    finished = {(j.metadata.namespace, j.metadata.name)
                for j in base.list(store_mod.TPUJOBS, namespace=NAMESPACE)
                if cond.is_finished(j.status)}
    in_flight_barriers = [
        key for key, b in getattr(holder["ckpt"], "_barriers", {}).items()
        if not b.outcome and key not in finished]
    if in_flight_barriers:
        violations.append(
            f"in-flight barriers left at convergence: "
            f"{in_flight_barriers}")

    durations = dur_acc + holder["timer"].snapshot()
    return {
        "convergence_seconds": round(convergence, 3),
        "jobs_per_sec": round(jobs / convergence, 2),
        "syncs": len(durations),
        "reconcile_p50_ms": round(_percentile(durations, 0.50) * 1e3, 3),
        "reconcile_p99_ms": round(_percentile(durations, 0.99) * 1e3, 3),
        "jobs": jobs,
        "workers_per_job": workers,
        "pods": jobs * workers,
        "threadiness": threadiness,
        "chaos_profile": profile_name,
        "chaos_seed": seed,
        "faults_injected": chaos.injector.snapshot(),
        "faults_injected_total": chaos.injector.total,
        "retries_total": int(
            sum(v for _, v in metrics.api_retries.collect())
            - retries_before),
        "degraded_entries": int(
            (sum(v for _, v in metrics.degraded_entries.collect()) or 0.0)
            - degraded_before),
        "crash_restarts": crashes_done,
        "disruptions": disruptions,
        "disruptions_injected": injected[0],
        "barriers_acked": barriers_acked,
        "barriers_timeout": barriers_timeout,
        "total_chips": total_chips,
        "max_admitted_chips": max_admitted[0],
        "elastic": elastic,
        "shrinks_landed": shrinks_landed[0],
        "rl": rl,
        "actors_per_job": actors if rl else 0,
        "actor_kill_storms": storms[0],
        "actor_kills": actor_kills[0],
        "learner_identities_tracked": len(learner_uids),
        "invariant_violations": violations,
    }


class RLWorldKubelet(threading.Thread):
    """Fake data plane for the RL actor–learner scenario: one training
    WORLD per job plus a free-floating actor pool, with membership
    derived from the POD SHAPE, not the role name — a pod whose default
    container carries ``JAX_PROCESS_ID`` joined the ranked
    jax.distributed world (bootstrap/cluster.py renders it only for
    ranked types); a pod without it (an RL actor) did not.

    Per tick, a job whose world members are ALL Running advances the
    job's step counter by one and charges one tick to the executed
    counter; world members publish CheckpointRecords on the periodic
    cadence. A world member that (re)starts with ``TPUJOB_RESTORE_STEP``
    rolls the WHOLE world back to that committed step — the re-executed
    steps are the honest waste of a world restart. Actor pods start,
    run, and die without touching any of that, which is exactly the
    asymmetry the goodput comparison measures:

        goodput_ratio = useful steps / total steps executed."""

    def __init__(self, store: Store, steps: int, tick: float = 0.01,
                 admitted=None, save_interval: int = 20):
        super().__init__(name="rl-kubelet", daemon=True)
        self.store = store
        self.steps = steps
        self.tick = tick
        self.admitted = admitted
        self.save_interval = save_interval
        self.progress: Dict[str, int] = {}    # job -> useful steps
        self.executed: Dict[str, int] = {}    # job -> ticks advanced
        self.last_save: Dict[str, int] = {}
        self.world_size: Dict[str, int] = {}  # max world members seen
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    @staticmethod
    def is_world_member(pod) -> bool:
        """Shape-derived world membership: the ranked-bootstrap env is
        present iff the role joined the jax.distributed world."""
        return any("JAX_PROCESS_ID" in c.env for c in pod.spec.containers)

    def run(self) -> None:
        from tf_operator_tpu.api.types import (
            CheckpointRecord,
            CheckpointRecordStatus,
        )

        while not self._stop.is_set():
            by_job: Dict[str, list] = {}
            for p in self.store.list(store_mod.PODS, namespace=NAMESPACE):
                if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                    continue
                jn = p.metadata.labels.get(constants.LABEL_JOB_NAME, "")
                by_job.setdefault(jn, []).append(p)
            for jn, pods in by_job.items():
                self._drive(jn, pods, CheckpointRecord,
                            CheckpointRecordStatus)
            self._stop.wait(self.tick)

    def _drive(self, job_name: str, pods, record_cls, status_cls) -> None:
        world = [p for p in pods if self.is_world_member(p)]
        for p in pods:
            if p.status.phase == PodPhase.PENDING:
                if (self.admitted is not None
                        and not self.admitted(p.metadata.namespace,
                                              job_name)):
                    continue
                self._start(p, job_name)
        running = [p for p in world if p.status.phase == PodPhase.RUNNING]
        idents = {(p.metadata.labels.get(constants.LABEL_REPLICA_TYPE),
                   p.metadata.labels.get(constants.LABEL_REPLICA_INDEX))
                  for p in world}
        self.world_size[job_name] = max(self.world_size.get(job_name, 0),
                                        len(idents))
        if job_name not in self.progress:
            return
        if (not running or len(running) != self.world_size[job_name]
                or len(world) != len(running)):
            return  # world incomplete: training paused, no steps burn
        progress = self.progress[job_name] + 1
        self.progress[job_name] = progress
        self.executed[job_name] = self.executed.get(job_name, 0) + 1
        if (progress - self.last_save.get(job_name, 0) >= self.save_interval
                or progress >= self.steps):
            self.last_save[job_name] = progress
            for p in running:
                self._publish(p, progress, record_cls, status_cls)
        if progress >= self.steps:
            for p in pods:  # actors included: the episode is over
                patch = Pod(metadata=ObjectMeta(
                    name=p.metadata.name,
                    namespace=p.metadata.namespace))
                patch.status = PodStatus(
                    phase=PodPhase.SUCCEEDED, start_time=testutil.now(),
                    container_statuses=[ContainerStatus(
                        name=constants.DEFAULT_CONTAINER_NAME,
                        state="Terminated", exit_code=0)])
                try:
                    self.store.update_status(store_mod.PODS, patch)
                except (store_mod.NotFoundError, store_mod.ConflictError):
                    pass

    def _start(self, pod, job_name: str) -> None:
        if self.is_world_member(pod):
            restore = None
            for c in pod.spec.containers:
                if constants.ENV_RESTORE_STEP in c.env:
                    restore = int(c.env[constants.ENV_RESTORE_STEP])
            if restore is not None:
                # World restart: everyone resumes from the committed
                # step; uncommitted progress past the last save is
                # re-executed (counted against goodput).
                self.progress[job_name] = restore
                self.last_save[job_name] = restore
            else:
                self.progress.setdefault(job_name, 0)
        patch = Pod(metadata=ObjectMeta(name=pod.metadata.name,
                                        namespace=pod.metadata.namespace))
        patch.status = PodStatus(phase=PodPhase.RUNNING,
                                 start_time=testutil.now())
        try:
            self.store.update_status(store_mod.PODS, patch)
        except (store_mod.NotFoundError, store_mod.ConflictError):
            pass

    def _publish(self, pod, step: int, record_cls, status_cls) -> None:
        ns, name = pod.metadata.namespace, pod.metadata.name
        status = status_cls(step=step, progress_step=step,
                            directory="/bench/ckpt", save_seconds=0.001,
                            updated_at=testutil.now())
        try:
            existing = self.store.try_get(store_mod.CHECKPOINTRECORDS,
                                          ns, name)
            if existing is None:
                self.store.create(store_mod.CHECKPOINTRECORDS, record_cls(
                    metadata=ObjectMeta(
                        name=name, namespace=ns,
                        labels=dict(pod.metadata.labels),
                        owner_references=[r.deepcopy() for r in
                                          pod.metadata.owner_references]),
                    status=status))
            else:
                existing.status = status
                self.store.update_status(store_mod.CHECKPOINTRECORDS,
                                         existing)
        except (store_mod.AlreadyExistsError, store_mod.ConflictError,
                store_mod.NotFoundError):
            pass


def _rl_once(heterogeneous: bool, learners: int, actors: int,
             threadiness: int, timeout: float, steps: int,
             save_interval: int, kill_rounds: int,
             kubelet_tick: float) -> Dict:
    """One RL sub-run. ``heterogeneous=True`` is the role-policy shape:
    ``learners`` barrier-class workers plus an explicit evict-class
    CPU-only actor pool. False is the homogeneous control: the SAME
    headcount, but the actor slots are plain workers — world members —
    so every kill storm is a world restart. Same kill schedule both
    ways; the goodput gap is the subsystem's value."""
    from tf_operator_tpu.api.types import (
        CheckpointPolicy,
        DisruptionClass,
        ReplicaType,
        RolePolicy,
    )
    from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
    from tf_operator_tpu.controller.engine import EngineConfig
    from tf_operator_tpu.controller.gang import (
        PHASE_INQUEUE,
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.runtime import metrics

    store = Store()
    ckpt = CheckpointCoordinator(store).start()
    gang = SliceGangScheduler(store, total_chips=None, ckpt=ckpt)
    ckpt.on_ack = gang.readmit
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=NAMESPACE, ckpt=ckpt)

    def group_admitted(ns: str, job_name: str) -> bool:
        g = store.try_get(store_mod.SLICEGROUPS, ns, job_name)
        return g is not None and g.status.phase in (PHASE_INQUEUE,
                                                    PHASE_RUNNING)

    kubelet = RLWorldKubelet(store, steps=steps, tick=kubelet_tick,
                             admitted=group_admitted,
                             save_interval=save_interval)
    name = "bench-rl-0000"
    metrics.job_goodput_ratio.set(0.0, job_namespace=NAMESPACE, job=name)
    metrics.learner_goodput_ratio.set(0.0, job_namespace=NAMESPACE,
                                      job=name)
    if heterogeneous:
        job = testutil.new_tpujob(worker=learners, actor=actors,
                                  name=name, namespace=NAMESPACE)
        job.spec.replica_specs[ReplicaType.ACTOR].role_policy = RolePolicy(
            chip_consuming=False, preemptible=True,
            min_replicas=1, max_replicas=actors,
            disruption_class=DisruptionClass.EVICT)
        metrics.actor_pool_replicas.set(actors, job_namespace=NAMESPACE,
                                        job=name, replica_type="actor")
    else:
        job = testutil.new_tpujob(worker=learners + actors, name=name,
                                  namespace=NAMESPACE)
    job.spec.slice.accelerator = "v5e-4"
    job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
        enabled=True, directory="/bench/ckpt",
        interval_steps=save_interval)
    violations: List[str] = []
    kills = [0]
    rounds_done = [0]
    stop_aux = threading.Event()

    def kill_targets():
        """Live pods the storm may kill: the actor pool in the
        heterogeneous run; the same POSITIONS (worker index >=
        learners) in the homogeneous control."""
        out = []
        for p in store.list(store_mod.PODS, namespace=NAMESPACE,
                            selector={constants.LABEL_JOB_NAME: name}):
            if p.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            rt = p.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
            idx = int(p.metadata.labels.get(
                constants.LABEL_REPLICA_INDEX, "0"))
            if heterogeneous:
                if rt == ReplicaType.ACTOR:
                    out.append(p)
            elif rt == ReplicaType.WORKER and idx >= learners:
                out.append(p)
        return sorted(out, key=lambda p: p.metadata.name)

    def storm() -> None:
        """The actor kill storm: ``kill_rounds`` rounds, each deleting
        at least half the pool at once — paced to land deep into the
        save window (>=75% of the interval uncommitted) so a world
        restart provably wastes work, and gated on the pool being whole
        again so every round hits a healed pool."""
        while not stop_aux.is_set() and rounds_done[0] < kill_rounds:
            prog = kubelet.progress.get(name, 0)
            if prog >= steps:
                break
            saved = kubelet.last_save.get(name, 0)
            window = prog - saved
            # Only storm a fully-RUNNING pool (each round hits a healed
            # world), only after the first committed save exists (or a
            # control-run restart has nothing to roll back to), and
            # only deep into the save window (>=75% uncommitted) so a
            # world restart provably wastes work.
            targets = [p for p in kill_targets()
                       if p.status.phase == PodPhase.RUNNING]
            if (saved <= 0 or window < int(save_interval * 0.75)
                    or len(targets) < actors):
                stop_aux.wait(kubelet_tick)
                continue
            for p in targets[:max(1, (actors + 1) // 2)]:
                if store.try_delete(store_mod.PODS, NAMESPACE,
                                    p.metadata.name):
                    kills[0] += 1
                    if heterogeneous:
                        metrics.actor_preemptions.inc(
                            job_namespace=NAMESPACE, reason="manual")
            rounds_done[0] += 1
            stop_aux.wait(kubelet_tick)

    # Learner (world-member) incarnations: identity -> uid first seen
    # Running. In the heterogeneous run a CHANGED uid is a violation —
    # actor churn must never restart the learner world. The control run
    # kills world members on purpose, so it only reports the count.
    world_uids: Dict[tuple, str] = {}
    learner_restarts = [0]
    committed_seen = [None]

    def probe() -> None:
        while not stop_aux.wait(0.02):
            for p in store.list(store_mod.PODS, namespace=NAMESPACE,
                                selector={constants.LABEL_JOB_NAME: name}):
                if p.status.phase != PodPhase.RUNNING:
                    continue
                if not RLWorldKubelet.is_world_member(p):
                    continue
                ident = (p.metadata.labels.get(
                    constants.LABEL_REPLICA_TYPE),
                    p.metadata.labels.get(constants.LABEL_REPLICA_INDEX))
                prev = world_uids.get(ident)
                if prev is None:
                    world_uids[ident] = p.metadata.uid
                elif prev != p.metadata.uid:
                    learner_restarts[0] += 1
                    world_uids[ident] = p.metadata.uid
                    if heterogeneous:
                        violations.append(
                            f"learner pod {ident} restarted (uid "
                            f"changed) during actor-only kill storms")
            records = [r.status.step for r in store.list(
                store_mod.CHECKPOINTRECORDS, namespace=NAMESPACE,
                selector={constants.LABEL_JOB_NAME: name})
                if r.status.step >= 0]
            if records:
                committed = min(records)
                prev = committed_seen[0]
                if prev is not None and committed < prev:
                    violations.append(
                        f"committed step regressed {prev} -> "
                        f"{committed} under the kill storm")
                committed_seen[0] = max(prev or 0, committed)

    controller.run(threadiness=threadiness)
    kubelet.start()
    storm_t = threading.Thread(target=storm, daemon=True, name="storm")
    probe_t = threading.Thread(target=probe, daemon=True, name="rl-probe")
    t0 = time.perf_counter()
    try:
        store.create(store_mod.TPUJOBS, job)
        storm_t.start()
        probe_t.start()
        deadline = t0 + timeout
        while True:
            succeeded = sum(store.project(
                store_mod.TPUJOBS,
                lambda j: 1 if cond.is_succeeded(j.status) else None,
                namespace=NAMESPACE))
            if succeeded >= 1:
                # Converged. Kill rounds are best-effort past this
                # point (no live pool left to storm) — the artifact
                # reports how many actually landed.
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"job not Succeeded after {timeout}s "
                    f"({rounds_done[0]}/{kill_rounds} kill rounds, "
                    f"step {kubelet.progress.get(name, 0)}/{steps})")
            time.sleep(0.02)
        convergence = time.perf_counter() - t0
    finally:
        stop_aux.set()
        kubelet.stop()
        controller.stop()
        ckpt.stop()
        store.stop_watchers()

    # Pod-shape evidence, from the store's final state: actor pods must
    # hold no chips, no ranked env, and a learner-endpoints env; the
    # control run has no such pods.
    for p in store.list(store_mod.PODS, namespace=NAMESPACE,
                        selector={constants.LABEL_JOB_NAME: name}):
        rt = p.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        if rt != "actor":
            continue
        if any(constants.RESOURCE_TPU in c.resources
               for c in p.spec.containers):
            violations.append(
                f"actor pod {p.metadata.name} was stamped with "
                f"{constants.RESOURCE_TPU} resources")
        if RLWorldKubelet.is_world_member(p):
            violations.append(
                f"actor pod {p.metadata.name} carries ranked world env")
        if not any(constants.ENV_LEARNER_ENDPOINTS in c.env
                   for c in p.spec.containers):
            violations.append(
                f"actor pod {p.metadata.name} missing "
                f"{constants.ENV_LEARNER_ENDPOINTS}")

    executed = kubelet.executed.get(name, 0)
    useful = min(steps, kubelet.progress.get(name, 0))
    return {
        "heterogeneous": heterogeneous,
        "convergence_seconds": round(convergence, 3),
        "steps": steps,
        "steps_executed": executed,
        "goodput_ratio": round(useful / executed, 4) if executed else 0.0,
        "kill_rounds": rounds_done[0],
        "kills": kills[0],
        "learner_restarts": learner_restarts[0],
        "committed_step_final": committed_seen[0],
        "learner_goodput_ratio_metric": round(
            metrics.learner_goodput_ratio.value(
                job_namespace=NAMESPACE, job=name), 4),
        "invariant_violations": violations,
    }


def run_rl_bench(learners: int, actors: int, threadiness: int,
                 timeout: float, save_interval: int = 20,
                 kill_rounds: int = 6,
                 kubelet_tick: float = 0.01) -> Dict:
    """RL actor–learner scenario (--rl, docs/rl.md): the SAME fleet
    shape and kill schedule run twice — once as a heterogeneous gang
    (barrier-class learners + an explicit evict-class CPU-only actor
    pool) and once as the homogeneous control (the actor slots are
    plain workers). Each kill round deletes at least half the pool
    mid-save-window. In the heterogeneous run the learner world must
    not notice (uid-stable learners, committed step monotonic, goodput
    ~1.0); the control run pays a world restart per round — the
    learner-goodput gap is the headline."""
    steps = (kill_rounds + 2) * save_interval
    control = _rl_once(False, learners, actors, threadiness, timeout,
                       steps, save_interval, kill_rounds, kubelet_tick)
    rl = _rl_once(True, learners, actors, threadiness, timeout,
                  steps, save_interval, kill_rounds, kubelet_tick)
    return {
        "learners": learners,
        "actors": actors,
        "kill_rounds": kill_rounds,
        "steps_per_run": steps,
        "save_interval_steps": save_interval,
        "threadiness": threadiness,
        "learner_goodput_ratio_rl": rl["goodput_ratio"],
        "learner_goodput_ratio_control": control["goodput_ratio"],
        "goodput_gap": round(
            rl["goodput_ratio"] - control["goodput_ratio"], 4),
        "rl": rl,
        "control": control,
        "invariant_violations": list(rl["invariant_violations"])
        + list(control["invariant_violations"]),
    }


def _environment() -> Dict:
    """Environment fingerprint fields (auditable round-over-round):
    jax version + platform/chip kind when jax is importable, host facts
    always. Importing jax is optional — the control plane needs none of
    it and smoke environments may not have it."""
    env = {
        "python": _platform.python_version(),
        "machine": _platform.machine(),
        "system": _platform.system(),
    }
    try:
        import jax

        env["jax_version"] = jax.__version__
        d = jax.devices()[0]
        env["platform"] = d.platform
        env["chip_kind"] = getattr(d, "device_kind", "") or d.platform
    except Exception:
        env["jax_version"] = None
        env["platform"] = "none"
        env["chip_kind"] = "none"
    return env


def config_fingerprint(config: Dict) -> str:
    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=200,
                   help="total jobs (plain scenario) or jobs PER TENANT "
                        "(--tenants scenario)")
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--threadiness", type=int, default=4)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--kubelet-tick", type=float, default=0.01)
    p.add_argument("--shards", type=int, default=0,
                   help="N>1 switches to the sharded control-plane "
                        "scenario: N shard leases, jobs hashed to "
                        "shards by (namespace, uid), a standby replica "
                        "contending, and (unless --no-kill-shard) one "
                        "shard of the primary crashed mid-run so the "
                        "standby re-acquires it; the artifact records "
                        "per-shard jobs/sec, reassignments, watch-"
                        "cache hit rate, failover seconds, and the "
                        "ownership evidence (docs/benchmarks.md)")
    p.add_argument("--kill-shard", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="(--shards) crash one of the primary replica's "
                        "shards once a third of the fleet converged "
                        "(lease not released; the standby waits out "
                        "expiry)")
    p.add_argument("--tenants", type=int, default=0,
                   help="N>0 switches to the multi-tenant contention "
                        "scenario: N tenant queues over one cohort, "
                        "gang admission + quota on, per-queue "
                        "admission-wait and reclaim counts in the "
                        "artifact")
    p.add_argument("--chips-per-job", type=int, default=4,
                   help="(--tenants) slice size per job = per-queue "
                        "nominal quota")
    p.add_argument("--disruptions", type=int, default=0,
                   help="N>0 switches to the disruption/goodput "
                        "scenario: checkpointing fake jobs with N "
                        "injected drains through the save-before-evict "
                        "barrier (controller/ckpt.py); barrier "
                        "outcomes, steps-lost, and goodput ratio in "
                        "the artifact")
    p.add_argument("--steps", type=int, default=80,
                   help="(--disruptions) fake training steps per job")
    p.add_argument("--save-interval", type=int, default=20,
                   help="(--disruptions) periodic-save cadence in steps")
    p.add_argument("--chaos", default=None,
                   choices=("off", "default", "heavy"),
                   help="switches to the chaos scenario: gang + "
                        "checkpoint barriers + disruptions reconciled "
                        "through a seeded FaultProfile "
                        "(runtime/chaos.py) with an operator "
                        "crash-restart mid-run; the artifact records "
                        "faults/retries/degraded entries and the "
                        "post-convergence invariant checks "
                        "(docs/robustness.md)")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="(--chaos) FaultProfile seed")
    p.add_argument("--crash-restarts", type=int, default=1,
                   help="(--chaos) operator crash-restarts to inject")
    p.add_argument("--elastic", action="store_true",
                   help="(--chaos) enable the elastic resize pass: "
                        "jobs declare minSlices/maxSlices, the grow "
                        "pass and a shrink exerciser churn resizes "
                        "through the faults, and the elastic "
                        "invariants (never below minSlices, budget "
                        "held mid-resize, every shrink barrier "
                        "resolved) are checked")
    p.add_argument("--rl", action="store_true",
                   help="switches to the RL actor–learner scenario "
                        "(docs/rl.md): one heterogeneous gang "
                        "(barrier-class learners + an explicit "
                        "evict-class CPU-only actor pool) and one "
                        "homogeneous control with the same headcount, "
                        "both under the same actor kill storms; the "
                        "artifact reports learner goodput for each "
                        "(acceptance: >=0.95 heterogeneous vs <=0.7 "
                        "control) plus the learner-stability "
                        "invariants")
    p.add_argument("--learners", type=int, default=2,
                   help="(--rl) barrier-class learner replicas")
    p.add_argument("--actors", type=int, default=4,
                   help="(--rl) actor-pool replicas")
    p.add_argument("--kill-rounds", type=int, default=6,
                   help="(--rl) kill storms; each deletes at least "
                        "half the pool mid-save-window")
    p.add_argument("--oversubscribe", type=int, default=0,
                   help="N>0 switches to the elastic oversubscribe "
                        "scenario (docs/elastic.md): N tenants over a "
                        "cluster holding one nominal slice each, same "
                        "staggered schedule run elastic vs static; "
                        "the artifact reports the aggregate-goodput "
                        "gain (acceptance: >=20% at the default "
                        "3-tenant shape)")
    p.add_argument("--work-units", type=int, default=480,
                   help="(--oversubscribe) work units per job (one "
                        "unit per slice per kubelet tick)")
    p.add_argument("--stagger", type=float, default=1.0,
                   help="(--oversubscribe) seconds between tenant "
                        "submissions")
    p.add_argument("--trace", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="(plain scenario) run with the flight recorder "
                        "on and emit the phase_attribution block "
                        "(queue_wait/sync/api_retry/barrier_wait/"
                        "binder); --no-trace is the baseline half of "
                        "the tracing-overhead A/B (docs/benchmarks.md)")
    args = p.parse_args(argv)

    config = {"jobs": args.jobs, "workers": args.workers,
              "threadiness": args.threadiness,
              "kubelet_tick": args.kubelet_tick}
    if args.shards > 1:
        config.update({"shards": args.shards,
                       "kill_shard": args.kill_shard})
        metric = (f"controlplane_sharded_convergence_jobs_per_sec"
                  f"[{args.jobs}x{args.workers} s{args.shards}]")
    elif args.oversubscribe > 0:
        config.update({"oversubscribe": args.oversubscribe,
                       "work_units": args.work_units,
                       "stagger": args.stagger,
                       "chips_per_slice": args.chips_per_job})
        metric = (f"controlplane_oversubscribe_goodput_gain"
                  f"[{args.oversubscribe}t w{args.work_units}]")
    elif args.rl:
        config.update({"rl": True, "learners": args.learners,
                       "actors": args.actors,
                       "kill_rounds": args.kill_rounds,
                       "save_interval": args.save_interval})
        metric = (f"controlplane_rl_learner_goodput"
                  f"[{args.learners}L+{args.actors}A "
                  f"k{args.kill_rounds}]")
    elif args.chaos is not None:
        config.update({"chaos": args.chaos, "seed": args.chaos_seed,
                       "crash_restarts": args.crash_restarts,
                       "elastic": args.elastic})
        metric = (f"controlplane_chaos_convergence_jobs_per_sec"
                  f"[{args.jobs}x{args.workers} {args.chaos}"
                  f"{' elastic' if args.elastic else ''}]")
    elif args.tenants > 0:
        config.update({"tenants": args.tenants,
                       "chips_per_job": args.chips_per_job})
        metric = (f"controlplane_tenant_convergence_jobs_per_sec"
                  f"[{args.tenants}t x {args.jobs}x{args.workers}]")
    elif args.disruptions > 0:
        config.update({"disruptions": args.disruptions,
                       "steps": args.steps,
                       "save_interval": args.save_interval})
        metric = (f"controlplane_disruption_goodput_ratio"
                  f"[{args.jobs}x{args.workers} d{args.disruptions}]")
    else:
        metric = (f"controlplane_convergence_jobs_per_sec"
                  f"[{args.jobs}x{args.workers}]")
    try:
        if args.shards > 1:
            result = run_sharded_bench(
                args.jobs, args.workers, args.shards, args.threadiness,
                args.timeout, kubelet_tick=args.kubelet_tick,
                kill_shard=args.kill_shard, trace=args.trace)
        elif args.oversubscribe > 0:
            result = run_oversubscribe_bench(
                args.oversubscribe, args.threadiness, args.timeout,
                chips_per_slice=args.chips_per_job,
                work_units=args.work_units, stagger=args.stagger,
                kubelet_tick=args.kubelet_tick)
        elif args.rl:
            result = run_rl_bench(
                args.learners, args.actors, args.threadiness,
                args.timeout, save_interval=args.save_interval,
                kill_rounds=args.kill_rounds,
                kubelet_tick=args.kubelet_tick)
        elif args.chaos is not None:
            result = run_chaos_bench(
                args.jobs, args.workers, args.threadiness, args.timeout,
                profile_name=args.chaos, seed=args.chaos_seed,
                disruptions=max(args.disruptions, 2),
                crash_restarts=args.crash_restarts,
                kubelet_tick=args.kubelet_tick,
                elastic=args.elastic)
        elif args.tenants > 0:
            result = run_tenant_bench(
                args.tenants, args.jobs, args.workers, args.threadiness,
                args.timeout, chips_per_job=args.chips_per_job,
                kubelet_tick=args.kubelet_tick)
        elif args.disruptions > 0:
            result = run_disruption_bench(
                args.jobs, args.workers, args.threadiness, args.timeout,
                disruptions=args.disruptions, steps=args.steps,
                save_interval=args.save_interval,
                kubelet_tick=args.kubelet_tick)
        else:
            result = run_bench(args.jobs, args.workers, args.threadiness,
                               args.timeout,
                               kubelet_tick=args.kubelet_tick,
                               trace=args.trace)
        if args.oversubscribe > 0:
            value, unit = result["goodput_gain_pct"], "percent"
        elif args.rl:
            value, unit = result["learner_goodput_ratio_rl"], "ratio"
        elif args.disruptions > 0:
            value, unit = result.get("goodput_ratio_mean"), "ratio"
        else:
            value, unit = result["jobs_per_sec"], "jobs/sec"
        print(json.dumps({
            "metric": metric,
            "value": value,
            "unit": unit,
            **result,
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        if (result.get("invariant_violations")
                or result.get("ownership_violations")):
            # Converged, but a chaos/ownership invariant broke: the
            # artifact carries the details; the exit code fails the run.
            return 1
        return 0
    except Exception as e:  # one JSON line, even on failure
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": "jobs/sec",
            "error": f"{type(e).__name__}: {e}",
            "env": _environment(),
            "config_fingerprint": config_fingerprint(config),
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
