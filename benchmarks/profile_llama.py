"""Full-step XLA profile of the Llama training step (round-5 roofline).

The round-4 verdict: ResNet got a rigorous device-time/bytes/FLOPs
ceiling statement, but the 46.2%-MFU Llama step and the 42%-MFU flash
kernel had none — nobody had shown whether the ~47 points to the
93%-MFU matmul probe are structural or recoverable. This tool captures
the exact ``bench_llama`` training step (570M decoder, GQA or MHA)
under ``jax.profiler.trace`` and aggregates the same per-category
step budget ``profile_step.py`` produces for ResNet.

Usage:
    python benchmarks/profile_llama.py [--kv-heads 4] [--steps 4]
        [--attention auto|flash|xla] [--out results.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from profile_step import parse_trace  # noqa: E402  (stdlib-only parser)


def build_step(batch: int, seq: int, kv_heads, attention: str,
               remat_policy: str = "full"):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.llama import (
        Llama,
        LlamaConfig,
        param_logical_axes,
    )
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
    from tf_operator_tpu.parallel.sharding import LLAMA_RULES
    from tf_operator_tpu.train.trainer import Trainer

    impl = "" if attention == "auto" else attention
    cfg = LlamaConfig(vocab_size=32768, hidden=1024, n_layers=24,
                      n_heads=16, n_kv_heads=kv_heads or 16, head_dim=128,
                      mlp_dim=4096, max_seq_len=seq, remat=True,
                      remat_policy=remat_policy, attention_impl=impl)
    mesh = make_mesh(MeshConfig(dp=-1))
    trainer = Trainer(model=Llama(cfg), param_axes_fn=param_logical_axes,
                      rules=LLAMA_RULES, mesh=mesh,
                      optimizer=optax.adamw(1e-4))
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((batch, seq + 1), jnp.int32)}
    ctx = use_mesh(mesh)
    ctx.__enter__()
    state, sh = trainer.init(rng, sample)
    step = trainer.make_train_step(sh, sample)
    tok = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
    batch_d = {"inputs": tok}
    nparams = sum(x.size for x in jax.tree.leaves(state.params))
    return step, state, batch_d, nparams, cfg


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--kv-heads", type=int, default=4,
                    help="GQA KV heads (4 = the 46.2%-MFU headline "
                         "config; 16/omit = MHA)")
    ap.add_argument("--attention", default="auto",
                    choices=("auto", "flash", "xla"))
    ap.add_argument("--remat-policy", default="save_attn",
                    choices=("full", "save_attn", "save_qkv", "mlp_only"),
                    help="save_attn is the shipped headline policy "
                         "(docs/benchmarks.md round-5 roofline)")
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace", default=None,
                    help="parse an existing trace instead of capturing")
    args = ap.parse_args()

    if args.trace:
        trace = args.trace
    else:
        import jax

        step, state, batch_d, nparams, cfg = build_step(
            args.batch, args.seq, args.kv_heads, args.attention,
            args.remat_policy)
        for _ in range(3):
            state, m = step(state, batch_d)
        float(m["loss"])  # host sync: block_until_ready lies on axon
        outdir = tempfile.mkdtemp(prefix="llama-profile-")
        with jax.profiler.trace(outdir):
            for _ in range(args.steps):
                state, m = step(state, batch_d)
            float(m["loss"])
        traces = sorted(glob.glob(os.path.join(
            outdir, "**", "*.trace.json.gz"), recursive=True),
            key=os.path.getmtime)
        if not traces:
            raise SystemExit(f"no trace produced under {outdir}")
        trace = traces[-1]
        print(f"trace: {trace}", file=sys.stderr)

    summary = parse_trace(trace, args.steps)
    # Replace ResNet-nominal fields with the Llama model-FLOPs budget.
    B, S = args.batch, args.seq
    if not args.trace:
        attn_fl = 3.5 * 4 * cfg.n_layers * cfg.n_heads * S * S \
            * cfg.head_dim / 2 * B
        model_tflop = (6 * nparams * B * S + attn_fl) / 1e12
        summary["params"] = nparams
        summary["nominal_tflop_per_step"] = round(model_tflop, 3)
        dev_s = summary["device_ms_per_step"] / 1e3
        summary["nominal_mfu_pct"] = round(
            model_tflop / dev_s / args.peak_tflops * 100, 1)
        summary["tokens_per_sec_device"] = round(B * S / dev_s)
    if not args.trace:
        # Only stamped for in-process captures: an external --trace may
        # have been recorded at a different config, and mislabeling it
        # would silently skew any per-token math over the JSON.
        summary["batch_size"] = B
        summary["config"] = {"kv_heads": args.kv_heads, "seq": S,
                             "attention": args.attention,
                             "remat_policy": args.remat_policy}
    out = json.dumps(summary, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
