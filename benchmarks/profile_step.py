"""Full-step XLA profile of the headline ResNet-50 bench step.

Round-2 left the headline characterized only by microbenches; this tool
captures the real thing: it runs bench.py's exact train step under
``jax.profiler.trace`` (which works through the axon tunnel — the plugin
emits a standard Chrome trace with per-op ``hlo_category``,
``bytes_accessed`` and ``model_flops``), then aggregates a step-time
budget:

  * per-HLO-category ms/step, achieved HBM r+w GB/s, TFLOP/s, % of step
  * time-weighted bandwidth histogram (the ceiling proof: what fraction
    of device time runs at what fraction of the 819 GB/s v5e HBM spec)
  * top individual fusions with shapes

Usage:  python benchmarks/profile_step.py [--steps 5] [--out results.json]

The parse half is pure-stdlib (gzip+json) so it runs anywhere; the trace
half needs the chip.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import PEAK_FLOPS, RESNET50_TRAIN_FLOPS_PER_IMAGE  # noqa: E402

HBM_GBPS = 819.0  # v5e public HBM spec
PEAK_TFLOPS = PEAK_FLOPS["v5e"] / 1e12
NOMINAL_TRAIN_TFLOP = RESNET50_TRAIN_FLOPS_PER_IMAGE * 256 / 1e12


def capture_trace(steps: int, outdir: str, stem: str = "conv7") -> str:
    """Run the exact bench.py step under the profiler; return the trace."""
    import jax

    from bench import build_bench_step

    step, state, batch = build_bench_step(batch_size=256, image_size=224,
                                          stem=stem)
    for _ in range(3):
        state, m = step(state, batch)
    float(m["loss"])  # host sync (block_until_ready returns early on axon)
    with jax.profiler.trace(outdir):
        for _ in range(steps):
            state, m = step(state, batch)
        float(m["loss"])
    traces = sorted(glob.glob(os.path.join(outdir, "**", "*.trace.json.gz"),
                              recursive=True), key=os.path.getmtime)
    if not traces:
        raise RuntimeError(f"profiler produced no trace under {outdir}")
    return traces[-1]


def parse_trace(path: str, steps: int, top: int = 20,
                with_long: bool = False) -> dict:
    """Aggregate the device 'XLA Ops' track into a step budget.

    ``top`` bounds the per-op rows (None = all); ``with_long`` attaches
    each row's truncated HLO long_name (operand shapes) so callers like
    profile_moe.py can classify fusions into model-level buckets.
    """
    with gzip.open(path) as f:
        data = json.load(f)
    ev = data["traceEvents"]
    device_pids = {e["pid"] for e in ev
                   if e.get("ph") == "M" and e.get("name") == "process_name"
                   and "TPU" in str(e.get("args", {}).get("name", ""))}
    op_tids = {(e["pid"], e["tid"]) for e in ev
               if e.get("ph") == "M" and e.get("name") == "thread_name"
               and e.get("args", {}).get("name") == "XLA Ops"
               and e["pid"] in device_pids}
    if not op_tids:
        # CPU fallback: the TFRT CPU client emits per-op events on its
        # own thread (names like "tf_XLATfrtCpuClient/..."), carrying
        # hlo_op but no hlo_category/bytes_accessed/model_flops — times
        # aggregate, byte/FLOP columns read 0. This keeps the profile
        # artifact schema pinnable by host-only tier-1 smoke runs
        # (tests/test_bench_moe.py); real budgets need the chip.
        op_tids = {(e["pid"], e["tid"]) for e in ev
                   if e.get("ph") == "M" and e.get("name") == "thread_name"
                   and "XLATfrtCpuClient" in
                   str(e.get("args", {}).get("name", ""))}
    ops = [e for e in ev if e.get("ph") == "X"
           and (e.get("pid"), e.get("tid")) in op_tids]
    if not ops:
        raise SystemExit(
            f"no device XLA-Ops events found in {path} — is this a "
            f"host-only trace, or a plugin with different track names?")

    cat = collections.defaultdict(lambda: [0.0, 0, 0, 0])
    per_op = collections.defaultdict(lambda: [0.0, 0, 0, 0, ""])
    hist = collections.defaultdict(float)
    tot_us = tot_b = tot_f = 0.0
    wrapper_us = 0.0
    for e in ops:
        a = e.get("args", {})
        b = int(a.get("bytes_accessed", 0))
        fl = int(a.get("model_flops", 0) or 0)
        catname = a.get("hlo_category", "?")
        # Control-flow wrapper events (scan loops) SPAN their body ops,
        # which appear as separate events on the same track — counting
        # both would double the step time (a scanned Llama step showed
        # +92% from exactly this). Report them separately.
        if catname in ("while", "conditional"):
            wrapper_us += e["dur"]
            continue
        # Async pairs (copy-start/copy-done, async-start/async-done)
        # both carry the full transfer's bytes_accessed — verified:
        # identical values per pair — so only the -done half counts as
        # HBM traffic anywhere (totals, categories, per-op rows).
        if catname.endswith("-start"):
            b = 0
        for agg, key in ((cat, catname), (per_op, e["name"])):
            g = agg[key]
            g[0] += e["dur"]; g[1] += 1; g[2] += b; g[3] += fl
        per_op[e["name"]][4] = a.get("long_name", "")[:200]
        tot_us += e["dur"]; tot_f += fl; tot_b += b
        if e["dur"] >= 10:  # histogram skips latency-bound micro-ops
            bw = b / e["dur"] * 1e6 / 1e9
            hist[min(int(bw // 100) * 100, 1100)] += e["dur"]  # 1100 = ">=1100"

    def rows(agg, top=None):
        out = []
        for name, g in sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]:
            us, n, b, fl = g[:4]
            out.append({
                "name": name,
                "ms_per_step": round(us / steps / 1000, 3),
                "ops_per_step": n // steps,
                "gbps": round(b / us * 1e6 / 1e9, 1) if us else 0.0,
                "tflops": round(fl / us * 1e6 / 1e12, 2) if us else 0.0,
                "pct": round(us / tot_us * 100, 1),
            })
        return out

    shape_of = {}
    for name, (_, _, _, _, ln) in per_op.items():
        m = re.search(r"= \(?([a-z0-9]+\[[^\]]*\])", ln)
        shape_of[name] = m.group(1) if m else "?"
    top_ops = rows(per_op, top=top)
    for r in top_ops:
        r["shape"] = shape_of.get(r["name"], "?")
        if with_long:
            r["long"] = per_op[r["name"]][4]

    hist_total = sum(hist.values()) or 1.0
    return {
        "steps": steps,
        "batch_size": 256,  # capture_trace's config; consumed by bench.py
        "control_flow_wrapper_ms_per_step": round(
            wrapper_us / steps / 1000, 2),
        "device_ms_per_step": round(tot_us / steps / 1000, 2),
        "bytes_per_step_gb": round(tot_b / steps / 1e9, 2),
        "model_tflop_per_step": round(tot_f / steps / 1e12, 3),
        "nominal_tflop_per_step": round(NOMINAL_TRAIN_TFLOP, 3),
        "aggregate_rw_gbps": round(tot_b / tot_us * 1e6 / 1e9, 1),
        "pct_of_hbm_spec": round(tot_b / tot_us * 1e6 / 1e9 / HBM_GBPS * 100, 1),
        "nominal_mfu_pct": round(NOMINAL_TRAIN_TFLOP * 1e12
                                 / (tot_us / steps * 1e-6) / (PEAK_TFLOPS * 1e12)
                                 * 100, 1),
        "perfect_bw_floor_ms": round(tot_b / steps / (HBM_GBPS * 1e9) * 1000, 1),
        "categories": rows(cat),
        "top_ops": top_ops,
        "bw_histogram_ms_per_step": {
            (f">={k}" if k >= 1100 else f"{k}-{k + 100}"):
                round(v / steps / 1000, 2)
            for k, v in sorted(hist.items())},
        "bw_histogram_pct": {
            (f">={k}" if k >= 1100 else f"{k}-{k + 100}"):
                round(v / hist_total * 100, 1)
            for k, v in sorted(hist.items())},
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=None,
                    help="steps to trace (capture mode, default 5); with "
                         "--trace, REQUIRED: the step count the trace was "
                         "captured with (per-step numbers divide by it)")
    ap.add_argument("--out", default=None, help="write JSON summary here")
    ap.add_argument("--trace", default=None,
                    help="parse an existing *.trace.json.gz instead of running")
    ap.add_argument("--stem", default="conv7", choices=("conv7", "s2d"),
                    help="ResNet stem variant to profile (capture mode)")
    args = ap.parse_args()
    if args.trace and args.steps is None:
        ap.error("--trace requires --steps (the capture-time step count)")
    if args.steps is not None and args.steps <= 0:
        ap.error("--steps must be positive")
    steps = args.steps if args.steps is not None else 5
    trace = args.trace or capture_trace(steps,
                                        tempfile.mkdtemp(prefix="jaxprof_"),
                                        stem=args.stem)
    summary = parse_trace(trace, steps)

    print(f"device time/step : {summary['device_ms_per_step']} ms")
    print(f"bytes/step       : {summary['bytes_per_step_gb']} GB "
          f"(r+w, as counted by XLA)")
    print(f"aggregate r+w BW : {summary['aggregate_rw_gbps']} GB/s "
          f"({summary['pct_of_hbm_spec']}% of {HBM_GBPS:.0f} GB/s spec)")
    print(f"nominal MFU      : {summary['nominal_mfu_pct']}%  "
          f"(model_flops counted by XLA: {summary['model_tflop_per_step']} "
          f"TFLOP vs nominal {summary['nominal_tflop_per_step']})")
    print(f"perfect-BW floor : {summary['perfect_bw_floor_ms']} ms/step")
    print(f"\n{'category':<26}{'ms/step':>9}{'ops':>6}{'GB/s':>8}"
          f"{'TFLOP/s':>9}{'%':>6}")
    for r in summary["categories"]:
        print(f"{r['name']:<26}{r['ms_per_step']:9.2f}{r['ops_per_step']:6d}"
              f"{r['gbps']:8.1f}{r['tflops']:9.2f}{r['pct']:6.1f}")
    print(f"\n{'top op':<26}{'ms/step':>9}{'GB/s':>8}  shape")
    for r in summary["top_ops"]:
        print(f"{r['name']:<26}{r['ms_per_step']:9.2f}{r['gbps']:8.1f}"
              f"  {r['shape']}")
    print("\ntime-weighted r+w bandwidth histogram (ops >=10us):")
    for k, pct in summary["bw_histogram_pct"].items():
        ms = summary["bw_histogram_ms_per_step"][k]
        print(f"  {k:>9} GB/s: {ms:6.2f} ms ({pct:5.1f}%)")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"\nwrote {args.out}")


if __name__ == "__main__":
    main()
