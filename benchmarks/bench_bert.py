"""BERT-base MLM training throughput (BASELINE BERT config payload).

Produced the BERT table in docs/benchmarks.md. Single chip:
    python benchmarks/bench_bert.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import timing  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--preset", default="base", choices=["base", "tiny"])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.bert import (
        Bert,
        bert_base,
        bert_tiny,
        mlm_loss,
        param_logical_axes,
    )
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
    from tf_operator_tpu.parallel.sharding import LLAMA_RULES
    from tf_operator_tpu.train.trainer import Trainer

    cfg = bert_base() if args.preset == "base" else bert_tiny(
        max_seq_len=args.seq)
    B, S = args.batch, args.seq
    mesh = make_mesh(MeshConfig(dp=-1))
    rng = jax.random.PRNGKey(0)
    data = np.random.default_rng(0)
    batch = {
        "inputs": jnp.asarray(data.integers(0, cfg.vocab_size, (B, S)),
                              jnp.int32),
        "targets": jnp.asarray(data.integers(0, cfg.vocab_size, (B, S)),
                               jnp.int32),
        "mask": jnp.asarray(data.random((B, S)) < 0.15, jnp.float32),
    }
    trainer = Trainer(model=Bert(cfg), param_axes_fn=param_logical_axes,
                      rules=LLAMA_RULES, mesh=mesh,
                      optimizer=optax.adamw(1e-4), loss_fn=mlm_loss)
    with use_mesh(mesh):
        state, sh = trainer.init(rng, batch)
        step = trainer.make_train_step(sh, batch)
        for _ in range(3):
            state, m = step(state, batch)
        float(m["loss"])

        # Two-block de-drifted timing (docs/benchmarks.md methodology).
        dt, dt_single, state = timing.timed_two_block_stateful(
            step, state, batch, args.steps)

    nparams = sum(x.size for x in jax.tree.leaves(state.params))
    print(json.dumps({
        "what": f"bert_{args.preset}_train",
        "params": nparams,
        "ms_per_step": round(dt * 1e3, 1),
        "ms_per_step_single_block": round(dt_single * 1e3, 1),
        "tokens_per_sec": round(B * S / dt),
        "mfu_6nd": round(6 * nparams * B * S / dt
                         / (args.peak_tflops * 1e12), 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
