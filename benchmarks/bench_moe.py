"""Mixtral-style MoE training throughput (round 5 — the last model
family without a measured number; round 6 adds ``--dispatch``).

A mid-size MoE decoder (8 experts, top-2, GShard capacity dispatch) on
one chip: ep=1 collapses the all-to-alls, but the dispatch/combine
machinery, router, capacity dropping, and aux loss all run exactly as in
the sharded path, so this prices the MoE machinery itself. Model MFU
counts ACTIVE parameters only (attention + top-k of the expert stack)
— the MoE selling point is exactly that inactive experts cost no
FLOPs, so counting them would flatter the number.

``--dispatch`` selects the routing implementation (numerics-equivalent;
tests/test_moe_dispatch.py): ``einsum`` = one-hot [T,E,C] dispatch/
combine einsums (the GShard formulation, round-5 headline), ``gather``
= argsort + gather/scatter token permutation (round-6 fast path; see
docs/benchmarks.md MoE roofline for the byte/FLOP budget).

    python benchmarks/bench_moe.py [--batch 8] [--seq 2048]
        [--dispatch einsum|gather]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import timing  # noqa: E402


def build_moe_step(preset: str, batch: int, seq: int,
                   dispatch: str = "einsum"):
    """The exact benchmarked MoE program: (step, state, batch_d, cfg,
    mesh_ctx). Shared with benchmarks/profile_moe.py so the profile is
    of this step, not a re-implementation that could drift."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.mixtral import (
        Mixtral,
        MixtralConfig,
        make_moe_lm_loss,
        param_logical_axes,
    )
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
    from tf_operator_tpu.parallel.sharding import MOE_RULES
    from tf_operator_tpu.train.trainer import Trainer

    if preset == "tiny":
        cfg = MixtralConfig(vocab_size=512, hidden=128, n_layers=2,
                            n_heads=4, n_kv_heads=2, head_dim=32,
                            mlp_dim=256, n_experts=4, experts_per_token=2,
                            max_seq_len=seq, remat=False,
                            rope_theta=10000.0, dispatch=dispatch)
    else:
        cfg = MixtralConfig(vocab_size=32768, hidden=1024, n_layers=8,
                            n_heads=16, n_kv_heads=4, head_dim=128,
                            mlp_dim=2048, n_experts=8, experts_per_token=2,
                            max_seq_len=seq, remat=True, dispatch=dispatch)
    mesh = make_mesh(MeshConfig(dp=-1))
    # make_moe_lm_loss attaches its own model_inputs_fn; Trainer
    # auto-detects it.
    trainer = Trainer(model=Mixtral(cfg), param_axes_fn=param_logical_axes,
                      rules=MOE_RULES, mesh=mesh,
                      optimizer=optax.adamw(1e-4),
                      loss_fn=make_moe_lm_loss(cfg.aux_loss_weight))
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((batch, seq + 1), jnp.int32)}
    ctx = use_mesh(mesh)
    ctx.__enter__()
    state, sh = trainer.init(rng, sample)
    step = trainer.make_train_step(sh, sample)
    tok = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
    return step, state, {"inputs": tok}, cfg, ctx


def active_param_count(cfg, nparams: int) -> int:
    """Active params: experts contribute k/E of their weights per token."""
    expert_params = 3 * cfg.hidden * cfg.mlp_dim * cfg.n_experts \
        * cfg.n_layers
    return int(nparams - expert_params * (
        1 - cfg.experts_per_token / cfg.n_experts))


def moe_step_flops(cfg, nparams: int, batch: int, seq: int) -> float:
    """Model FLOPs/step credited by the MFU metric: 6·active·tokens +
    causal attention (same formula the dense Llama bench uses)."""
    active = active_param_count(cfg, nparams)
    attn_fl = 3.5 * 4 * cfg.n_layers * cfg.n_heads * seq * seq \
        * cfg.head_dim / 2 * batch
    return 6 * active * batch * seq + attn_fl


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--preset", default="512m", choices=["512m", "tiny"],
                    help="tiny = CPU-smoke-sized model")
    ap.add_argument("--dispatch", default="einsum",
                    choices=["einsum", "gather"],
                    help="MoE routing implementation (MixtralConfig."
                         "dispatch); numerics-equivalent")
    args = ap.parse_args(argv)

    import jax

    from bench import bench_config_fingerprint, bench_environment, detect_chip

    step, state, batch_d, cfg, ctx = build_moe_step(
        args.preset, args.batch, args.seq, args.dispatch)
    B, S = args.batch, args.seq
    for _ in range(3):
        state, m = step(state, batch_d)
    float(m["loss"])  # host sync (block_until_ready lies on axon)
    dt, dt_single, state = timing.timed_two_block_stateful(
        step, state, batch_d, args.steps)
    ctx.__exit__(None, None, None)

    nparams = sum(x.size for x in jax.tree.leaves(state.params))
    active = active_param_count(cfg, nparams)
    flops = moe_step_flops(cfg, nparams, B, S)
    config = {"preset": args.preset, "batch": B, "seq": S,
              "steps": args.steps, "dispatch": args.dispatch,
              "capacity_factor": cfg.capacity_factor,
              "n_experts": cfg.n_experts,
              "experts_per_token": cfg.experts_per_token}
    print(json.dumps({
        "what": f"mixtral{nparams // 1_000_000}m_moe_train[top"
                f"{cfg.experts_per_token}of{cfg.n_experts}]",
        "dispatch": args.dispatch,
        "ms_per_step": round(dt * 1e3, 1),
        "ms_per_step_single_block": round(dt_single * 1e3, 1),
        "tokens_per_sec": round(B * S / dt),
        "params_total": nparams,
        "params_active": active,
        "model_mfu_active": round(flops / dt / (args.peak_tflops * 1e12),
                                  3),
        "env": bench_environment(detect_chip()),
        "config_fingerprint": bench_config_fingerprint(config),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
