"""ResNet-50 normalization-scheme experiment (round-2 verdict item #3).

The round-1 platform characterization (BASELINE.md) showed the bench
chip's VPU/reduce ceiling (~21-27 G elem/s) makes BatchNorm statistics
the dominant step cost (47 of 99 ms). This benchmark runs the
"different normalization scheme" experiments that analysis pointed at,
measuring for each variant:

- images/sec (median of 3 timed reps, spread reported), and
- a loss-curve accuracy proxy: training loss trajectory over >=100
  steps on a fixed synthetic stream, compared against the f32-BN
  baseline curve.

Variants:
    bn           f32-statistics batch norm (baseline)
    bn_bf16      bf16-statistics accumulation (halves convert traffic)
    group        GroupNorm(32) — no batch statistics across samples
    bn_every_4   interval statistics: 1 stats step, 3 frozen-stats steps
    affine       per-channel scale/bias only — NOT a training scheme;
                 upper-bound probe for norm-free formulations

    python benchmarks/bench_norm.py --steps 20 --loss-steps 120
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(variant: str, batch_size: int, image_size: int,
          tiny: bool = False):
    import jax
    import jax.numpy as jnp
    import optax

    from tf_operator_tpu.models import resnet as rn
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
    from tf_operator_tpu.parallel.sharding import CNN_RULES
    from tf_operator_tpu.train.trainer import (
        Trainer,
        classification_loss,
        classification_loss_frozen_stats,
    )

    norm = {"bn": "bn", "bn_bf16": "bn_bf16", "group": "group",
            "bn_every_4": "bn", "affine": "affine"}[variant]
    import dataclasses as _dc

    base_cfg = rn.resnet_tiny() if tiny else rn.resnet50()
    cfg = _dc.replace(base_cfg, norm=norm)
    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1])

    def make_trainer(loss_fn):
        return Trainer(model=rn.ResNet(cfg),
                       param_axes_fn=rn.param_logical_axes,
                       rules=CNN_RULES, mesh=mesh,
                       optimizer=optax.sgd(0.1, momentum=0.9),
                       loss_fn=loss_fn, grad_norm_metric=False)

    trainer = make_trainer(classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=batch_size,
                               image_size=image_size,
                               num_classes=cfg.num_classes)
    batch["inputs"] = batch["inputs"].astype(jnp.bfloat16)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, shardings = trainer.init(rng, batch)
    stats_step = trainer.make_train_step(shardings, batch)
    frozen_step = None
    if variant == "bn_every_4":
        frozen_step = make_trainer(
            classification_loss_frozen_stats).make_train_step(
                shardings, batch)
    return state, batch, stats_step, frozen_step


def step_schedule(variant: str, stats_step, frozen_step):
    """Per-step callable sequence for one macro-cycle of the variant."""
    if variant == "bn_every_4":
        return [stats_step, frozen_step, frozen_step, frozen_step]
    return [stats_step]


def run_variant(variant: str, batch_size: int, image_size: int,
                steps: int, loss_steps: int, loss_every: int,
                tiny: bool = False):
    import jax

    num_classes = 10 if tiny else 1000
    state, batch, stats_step, frozen_step = build(variant, batch_size,
                                                  image_size, tiny)
    cycle = step_schedule(variant, stats_step, frozen_step)

    # Warmup both compiled paths.
    for fn in cycle:
        state, metrics = fn(state, batch)
    float(metrics["loss"])

    # Timing: median of 3 reps of `steps` steps walking the schedule.
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = cycle[i % len(cycle)](state, batch)
        float(metrics["loss"])
        rates.append(batch_size * steps / (time.perf_counter() - t0))
    rates.sort()
    median = rates[len(rates) // 2]
    spread = (rates[-1] - rates[0]) / median

    # Loss curve: fresh state, fixed data stream (new synthetic batch per
    # step from a fixed seed so every variant sees identical data).
    import jax.numpy as jnp

    from tf_operator_tpu.models import resnet as rn

    state, _, stats_step, frozen_step = build(variant, batch_size,
                                              image_size, tiny)
    cycle = step_schedule(variant, stats_step, frozen_step)
    losses = []
    for i in range(loss_steps):
        b = rn.synthetic_batch(jax.random.PRNGKey(1000 + i),
                               batch_size=batch_size,
                               image_size=image_size,
                               num_classes=num_classes)
        b["inputs"] = jnp.asarray(b["inputs"]).astype(jnp.bfloat16)
        b["labels"] = jnp.asarray(b["labels"])
        state, metrics = cycle[i % len(cycle)](state, b)
        if (i + 1) % loss_every == 0 or i == 0:
            losses.append((i + 1, round(float(metrics["loss"]), 4)))
    return {
        "images_per_sec": round(median, 2),
        "spread_frac": round(spread, 4),
        "loss_curve": losses,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--variants", default="bn,bn_bf16,group,bn_every_4,affine")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--loss-steps", type=int, default=120)
    ap.add_argument("--loss-every", type=int, default=10)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CPU smoke")
    args = ap.parse_args()
    if args.smoke:
        args.batch, args.image_size = 8, 32
        args.steps, args.loss_steps, args.loss_every = 3, 8, 2

    results = {}
    for variant in args.variants.split(","):
        variant = variant.strip()
        t0 = time.perf_counter()
        results[variant] = run_variant(variant, args.batch,
                                       args.image_size, args.steps,
                                       args.loss_steps, args.loss_every,
                                       tiny=args.smoke)
        results[variant]["wall_s"] = round(time.perf_counter() - t0, 1)
        print(json.dumps({variant: results[variant]}), flush=True)

    base = results.get("bn")
    if base:
        for variant, r in results.items():
            r["speedup_vs_bn"] = round(
                r["images_per_sec"] / base["images_per_sec"], 3)
            # Accuracy proxy: max |Δloss| against the baseline curve at
            # matching steps (identical data stream).
            base_curve = dict(base["loss_curve"])
            deltas = [abs(loss - base_curve[s])
                      for s, loss in r["loss_curve"] if s in base_curve]
            r["max_loss_delta_vs_bn"] = round(max(deltas), 4) if deltas else None
    print(json.dumps(results, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
