"""Decoder training throughput benchmark (the Llama BASELINE family).

Produced the Llama table in docs/benchmarks.md: a 570M-param decoder,
single chip, bf16 compute / f32 state, remat on. Compare attention
paths with --attention {auto,flash,xla,ring}.

    python benchmarks/bench_llama.py --attention auto
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import timing  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--attention", default="",
                    choices=["", "auto", "flash", "xla", "ring"])
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--preset", default="570m", choices=["570m", "tiny"],
                    help="tiny = CPU-smoke-sized model")
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="override n_kv_heads (GQA; default = n_heads)")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "save_attn", "save_qkv", "mlp_only"],
                    help="remat granularity (mlp_only keeps attention "
                         "activations; see LlamaConfig.remat_policy)")
    args = ap.parse_args()
    impl = "" if args.attention == "auto" else args.attention

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.llama import (
        Llama,
        LlamaConfig,
        param_logical_axes,
    )
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
    from tf_operator_tpu.parallel.sharding import LLAMA_RULES
    from tf_operator_tpu.train.trainer import Trainer

    if args.preset == "tiny":
        cfg = LlamaConfig(vocab_size=512, hidden=128, n_layers=2,
                          n_heads=4, n_kv_heads=4, head_dim=32, mlp_dim=256,
                          max_seq_len=args.seq, remat=False,
                          attention_impl=impl, rope_theta=10000.0)
    else:
        cfg = LlamaConfig(vocab_size=32768, hidden=1024, n_layers=24,
                          n_heads=16, n_kv_heads=16, head_dim=128,
                          mlp_dim=4096, max_seq_len=args.seq, remat=True,
                          remat_policy=args.remat_policy,
                          attention_impl=impl)
    if args.kv_heads is not None:
        import dataclasses

        if args.kv_heads < 1 or cfg.n_heads % args.kv_heads:
            ap.error(f"--kv-heads must divide n_heads={cfg.n_heads}; "
                     f"got {args.kv_heads}")
        cfg = dataclasses.replace(cfg, n_kv_heads=args.kv_heads)
    B, S = args.batch, args.seq
    sp = 2 if impl == "ring" else 1
    mesh = make_mesh(MeshConfig(dp=-1, sp=sp))
    trainer = Trainer(model=Llama(cfg), param_axes_fn=param_logical_axes,
                      rules=LLAMA_RULES, mesh=mesh,
                      optimizer=optax.adamw(1e-4))
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((B, S + 1), jnp.int32)}
    with use_mesh(mesh):
        state, sh = trainer.init(rng, sample)
        step = trainer.make_train_step(sh, sample)
        tok = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (B, S + 1)), jnp.int32)
        for _ in range(3):
            state, m = step(state, {"inputs": tok})
        float(m["loss"])

        # Two-block de-drifted timing (docs/benchmarks.md methodology
        # note): the tunnel charges ~90 ms fixed sync per block.
        dt, dt_single, state = timing.timed_two_block_stateful(
            step, state, {"inputs": tok}, args.steps)

    nparams = sum(x.size for x in jax.tree.leaves(state.params))
    attn_fl = 3.5 * 4 * cfg.n_layers * cfg.n_heads * S * S \
        * cfg.head_dim / 2 * B
    flops = 6 * nparams * B * S + attn_fl
    print(json.dumps({
        "what": f"llama{nparams // 1_000_000}m_train[{args.attention or 'auto'}]",
        "ms_per_step": round(dt * 1e3, 1),
        "ms_per_step_single_block": round(dt_single * 1e3, 1),
        "tokens_per_sec": round(B * S / dt),
        "params": nparams,
        "model_mfu": round(flops / dt / (args.peak_tflops * 1e12), 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
