"""Shared benchmark timing/setup harness.

Two platform quirks every bench must handle (docs/benchmarks.md,
"Timing methodology note"):

- ``jax.block_until_ready`` can return early on the tunneled PJRT
  plugin, so syncing is a host transfer (``float()``);
- the tunnel charges a large fixed sync cost (~90 ms) per timing block,
  so per-call time is extrapolated from two block sizes:
  t(n) = t_call + C/n  =>  t_call = (n2·T2 − n1·T1)/(n2 − n1).

``setup(cpu_mesh=True)`` re-execs the process with a CPU backend and 8
virtual devices when the current XLA_FLAGS don't already pin that exact
device count (the axon sitecustomize initializes the backend before
user code runs, so mutating the env in-process is too late).
"""

from __future__ import annotations

import os
import re
import sys
import time

CPU_MESH_DEVICES = 8
_COUNT_FLAG = "--xla_force_host_platform_device_count"


def setup(cpu_mesh: bool):
    """Import-and-return jax, re-execing first when a CPU mesh of
    CPU_MESH_DEVICES is requested but not active."""
    if cpu_mesh:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
        if m is None or int(m.group(1)) != CPU_MESH_DEVICES:
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", "", flags).strip()
            os.environ["XLA_FLAGS"] = (
                f"{flags} {_COUNT_FLAG}={CPU_MESH_DEVICES}".strip())
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.execv(sys.executable, [sys.executable] + sys.argv)
    import jax

    if cpu_mesh:
        jax.config.update("jax_platforms", "cpu")
    return jax


def sync(out) -> None:
    """Host-transfer sync (block_until_ready is unreliable here)."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.ravel()[0])


def _block(fn, args, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return time.perf_counter() - t0


def timed(fn, *args, warm: int = 2, n1: int = 5, n2: int = 25) -> float:
    """Two-point extrapolated per-call seconds."""
    out = None
    for _ in range(warm):
        out = fn(*args)
    sync(out)
    t1 = _block(fn, args, n1)
    t2 = _block(fn, args, n2)
    return max((t2 - t1) / (n2 - n1), 1e-9)


def timed_two_block(run_block, steps: int):
    """De-drift for STATEFUL step loops (training benches): the caller's
    ``run_block(n)`` executes n steps with a trailing host sync and
    returns elapsed seconds. Returns (per_step_seconds,
    single_block_per_step) from a 1x and a 3x block."""
    t1 = run_block(steps)
    t3 = run_block(3 * steps)
    return max((t3 - t1) / (2 * steps), 1e-9), t1 / steps


def timed_two_block_stateful(step, state, batch, steps: int):
    """timed_two_block for the common (state, metrics) = step(state,
    batch) training-loop shape; syncs on metrics["loss"]. Returns
    (per_step_seconds, single_block_per_step, final_state)."""
    box = [state]

    def run_block(n):
        t0 = time.perf_counter()
        st = box[0]
        for _ in range(n):
            st, m = step(st, batch)
        float(m["loss"])
        box[0] = st
        return time.perf_counter() - t0

    dt, dt_single = timed_two_block(run_block, steps)
    return dt, dt_single, box[0]
