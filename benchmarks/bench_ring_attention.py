"""Ring attention measurement (round-2 verdict item #7).

One real chip cannot host an sp>1 ring, so the measurement splits:

- default (bench chip): sp=1 equivalence + timing — the degenerate
  one-step ring against the Pallas flash path and XLA attention on the
  same shapes. Quantifies the online-softmax machinery's overhead and
  pins numerics on real hardware.
- ``--cpu-mesh``: 8 virtual CPU devices; sp in {2,4,8} numerics vs the
  dense reference (exactness of the block-online softmax across ring
  steps) plus relative step time.
- both modes print the analytic ICI scaling model: per-device ppermute
  traffic is 2·(sp-1)/sp·B·S·H·D·2 bytes per attention (K and V blocks,
  sp-1 hops), while per-device compute is O(S²/sp) — so the ring's
  comm:compute ratio FALLS with S and ring attention is the asymptotic
  win for long context (the measured 42% MFU single-chip flash at 32k
  feeds the model's compute term).

    python benchmarks/bench_ring_attention.py            # chip
    python benchmarks/bench_ring_attention.py --cpu-mesh # sp numerics
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# v5e: 4 ICI links/chip at ~100 GB/s realized aggregate per direction is
# optimistic; use the public per-link ~45 GB/s and 1 link per ring hop.
ICI_GBPS = 45.0
FLASH_32K_MFU = 0.42        # measured, docs/benchmarks.md
V5E_PEAK_TFLOPS = 197.0


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from timing import setup as _setup, timed  # noqa: E402


def scaling_model(b, s, h, d, sp):
    """Analytic comm/compute for one causal ring attention."""
    bytes_per_dev = 2 * (sp - 1) * (b * (s // sp) * h * d * 2)  # K+V, bf16
    comm_s = bytes_per_dev / (ICI_GBPS * 1e9)
    flops_per_dev = 4 * b * h * d * (s ** 2) / 2 / sp  # causal half
    compute_s = flops_per_dev / (FLASH_32K_MFU * V5E_PEAK_TFLOPS * 1e12)
    return {
        "sp": sp, "seq": s,
        "ppermute_mb_per_dev": round(bytes_per_dev / 2**20, 1),
        "ici_ms": round(comm_s * 1e3, 3),
        "compute_ms_at_42pct_mfu": round(compute_s * 1e3, 3),
        "comm_over_compute": round(comm_s / compute_s, 4),
    }


def run_chip():
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.compat import shard_map
    from tf_operator_tpu.ops.flash_attention import best_attention
    from tf_operator_tpu.ops.ring_attention import ring_attention
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshConfig(sp=1), devices=jax.devices()[:1])
    h, d = 16, 128
    for b, s in ((8, 2048), (2, 8192)):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.bfloat16)
                   for kk in ks)

        def ring1(q, k, v):
            fn = shard_map(
                lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
                mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                check_vma=False)
            return fn(q, k, v)

        from tf_operator_tpu.ops.ring_attention import ring_flash_attention

        def ringf1(q, k, v):
            fn = shard_map(
                lambda q, k, v: ring_flash_attention(q, k, v,
                                                     axis_name="sp"),
                mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
                check_vma=False)
            return fn(q, k, v)

        ring_full = jax.jit(ring1)
        flash_full = jax.jit(lambda q, k, v: best_attention(q, k, v,
                                                            causal=True))
        err = float(jnp.max(jnp.abs(
            ring_full(q, k, v).astype(jnp.float32)
            - flash_full(q, k, v).astype(jnp.float32))))
        err_f = float(jnp.max(jnp.abs(
            jax.jit(ringf1)(q, k, v).astype(jnp.float32)
            - flash_full(q, k, v).astype(jnp.float32))))
        # Timing reduces to a scalar inside jit (bench_attention.py
        # methodology) so output materialization doesn't skew either path.
        ring_j = jax.jit(lambda q, k, v: ring1(q, k, v)
                         .astype(jnp.float32).sum())
        ringf_j = jax.jit(lambda q, k, v: ringf1(q, k, v)
                          .astype(jnp.float32).sum())
        flash_j = jax.jit(lambda q, k, v: best_attention(q, k, v,
                                                         causal=True)
                          .astype(jnp.float32).sum())
        t_ring = timed(ring_j, q, k, v)
        t_ringf = timed(ringf_j, q, k, v)
        t_flash = timed(flash_j, q, k, v)
        print(json.dumps({
            "mode": "chip-sp1", "batch": b, "seq": s,
            "ring_einsum_ms": round(t_ring * 1e3, 2),
            "ring_flash_ms": round(t_ringf * 1e3, 2),
            "flash_ms": round(t_flash * 1e3, 2),
            "ring_einsum_over_flash": round(t_ring / t_flash, 2),
            "ring_flash_over_flash": round(t_ringf / t_flash, 2),
            "max_abs_err": round(err, 5),
            "max_abs_err_flashring": round(err_f, 5),
        }), flush=True)
    for sp in (2, 4):
        print(json.dumps({"mode": "model"} | scaling_model(1, 32768, h, d,
                                                           sp)), flush=True)
    print(json.dumps({"mode": "model"} | scaling_model(1, 131072, h, d, 4)),
          flush=True)


def run_cpu_mesh():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.ops.ring_attention import ring_attention_sharded
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    b, s, h, d = 2, 512, 4, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in ks)

    # Dense causal reference.
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * d ** -0.5
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = jnp.einsum("bhst,bthd->bshd",
                     jax.nn.softmax(jnp.where(mask[None, None], logits,
                                              -1e30), axis=-1), v)

    for sp in (2, 4, 8):
        mesh = make_mesh(MeshConfig(sp=sp), devices=jax.devices()[:sp])
        fn = jax.jit(lambda q, k, v, mesh=mesh: ring_attention_sharded(
            mesh, q, k, v, causal=True))
        out = fn(q, k, v)
        err = float(np.max(np.abs(np.asarray(out) - np.asarray(ref))))
        t = timed(fn, q, k, v)
        print(json.dumps({"mode": f"cpu-sp{sp}", "seq": s,
                          "max_abs_err_vs_dense": round(err, 7),
                          "step_ms": round(t * 1e3, 2)}), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu-mesh", action="store_true")
    args = ap.parse_args()
    _setup(args.cpu_mesh)
    if args.cpu_mesh:
        run_cpu_mesh()
    else:
        run_chip()
    sys.exit(0)
