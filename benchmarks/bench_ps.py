"""Parameter-server throughput envelope (round-5 verdict #6; round 6
adds the multi-shard scaling row).

Round 4 shipped the PS runtime functional but unquantified. This bench
measures the full worker step cycle — pull all params, push all
gradients — against in-process sharded servers over loopback HTTP
(the same stdlib wire path production uses), sweeping parameter size,
worker count, AND shard count, and reports the sequential-vs-concurrent
shard fan-out comparison that motivated PSClient's thread-per-shard IO.

``--shards`` takes a comma list: the multi-shard rows at a fixed total
parameter size (e.g. 4 shards × ~12.5 MB vs 1 shard × 50 MB) measure
the documented "scale shard count, not workers per shard" remedy —
each shard applies pushes under its own lock in its own server, so
shard count is the axis that recovers steps/s for bigger models
(docs/benchmarks.md "Parameter-server envelope").

    python benchmarks/bench_ps.py [--sizes-mb 1,10,50] [--workers 1,4]
        [--shards 1,4]

Emits a JSON table; docs/benchmarks.md carries the measured envelope.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tf_operator_tpu.train.ps import (  # noqa: E402
    ParameterServer,
    PSClient,
    flatten_params,
)


def make_params(total_mb: float, n_tensors: int = 32) -> dict:
    """n float32 tensors summing to ~total_mb."""
    per = max(1, int(total_mb * (1 << 20) / 4 / n_tensors))
    return {f"layer{i}": {"w": np.random.default_rng(i).standard_normal(
        per).astype(np.float32)} for i in range(n_tensors)}


def run_case(size_mb: float, n_workers: int, n_shards: int,
             seconds: float, concurrent_shards: bool) -> dict:
    import optax

    servers = [ParameterServer(optimizer=optax.sgd(0.01),
                               host="127.0.0.1").serve()
               for _ in range(n_shards)]
    addrs = [f"127.0.0.1:{s.port}" for s in servers]
    try:
        params = make_params(size_mb)
        flat = flatten_params(params)
        nbytes = sum(v.nbytes for v in flat.values())
        PSClient(addrs).init(params)
        grads = params  # same structure/size

        counts = [0] * n_workers
        stop = threading.Event()

        def worker(i: int) -> None:
            client = PSClient(addrs)
            if not concurrent_shards:
                client._fan_out = lambda calls: [
                    fn(*args) for fn, *args in calls]
            while not stop.is_set():
                client.pull()
                client.push(grads)
                counts[i] += 1

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(n_workers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        dt = time.monotonic() - t0
        steps = sum(counts)
        return {
            "params_mb": round(nbytes / (1 << 20), 1),
            "workers": n_workers,
            "shards": n_shards,
            "shard_io": "concurrent" if concurrent_shards else "sequential",
            "steps_per_sec_total": round(steps / dt, 1),
            "steps_per_sec_per_worker": round(steps / dt / n_workers, 1),
            # One step moves params down + grads up.
            "wire_mb_per_sec": round(steps * 2 * nbytes / (1 << 20) / dt, 1),
        }
    finally:
        for s in servers:
            s.stop()


def main() -> int:
    # The PS runtime is CPU-oriented (host-side optax updates); without
    # this, optax dispatches every shard update to the TPU through the
    # tunnel and the bench measures the tunnel instead of the runtime.
    import jax

    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,10,50")
    ap.add_argument("--workers", default="1,4")
    ap.add_argument("--shards", default="2",
                    help="comma list; same TOTAL size spreads over more "
                         "shards (the scale-shard-count remedy)")
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args()
    rows = []
    for size in (float(s) for s in args.sizes_mb.split(",")):
        for ns in (int(s) for s in args.shards.split(",")):
            for nw in (int(w) for w in args.workers.split(",")):
                for conc in (False, True):
                    row = run_case(size, nw, ns, args.seconds, conc)
                    rows.append(row)
                    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
