"""Benchmark: serving-plane throughput and tail latency.

Drives a synthetic request load through the in-process ServingEngine
(request queue -> continuous batcher -> runner) and prints exactly ONE
JSON line, bench.py conventions:

    {"metric": "serving_tokens_per_sec[fake|llama-tiny]", "value": N,
     "unit": "tokens/sec", "qps": ..., "ttft_p50_s": ..., "ttft_p99_s":
     ..., "queue_depth_max": ..., "requests": ..., "completed": ...,
     "rejected": ..., "env": {...}, "config_fingerprint": "..."}

The p50/p99 TTFT come from the serving_ttft_seconds histogram via
Histogram.quantile (runtime/metrics.py) — the same numbers a scrape +
histogram_quantile() would produce. Arrivals are open-loop at --qps
(deterministic inter-arrival jitter off a seed), split across --tenants
weighted lanes, so queue_depth_max reflects genuine burst backpressure
rather than lock-step submission.

Runner "fake" is the deterministic jax-free generator (tier-1 smoke,
pinned by tests/test_bench_serving.py); "llama"/"mixtral" run the real
incremental-decode paths on tiny models (runners come from the
serve/worker.py registry — the bench and the production worker share
one factory).

``--scenario diurnal`` is the autoscaler proof (ROADMAP item 3(a)
acceptance; docs/serving.md): a diurnal/bursty request trace is fed
through the REAL HTTP gateway into a spool, and the SAME trace runs
twice — once with the serving autoscaler governing an elastic gang
through the real gang-scheduler resize pass, once statically
provisioned at the peak slice count. Serving capacity is a rate-based
fleet simulator over the spool (capacity tracks the job's live
``numSlices``, with a restart pause while a resize settles — real
tiny-model decode on one CPU host would not scale with slice count,
the same honesty trade as bench_controlplane's WorkUnitKubelet). The
artifact compares chip-seconds integrals and p99 TTFT against the
job's `ttftP99SloSeconds`; the acceptance floor at the default shape
is >=30% chip-seconds saved with the SLO held and zero dropped
requests across every resize.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tf_operator_tpu.runtime import metrics  # noqa: E402
from tf_operator_tpu.runtime import retry as retry_mod  # noqa: E402
from tf_operator_tpu.runtime import store as store_mod  # noqa: E402
from tf_operator_tpu.serve.batcher import ContinuousBatcher  # noqa: E402
from tf_operator_tpu.serve.engine import ServingEngine  # noqa: E402
from tf_operator_tpu.serve.queue import Request, RequestQueue  # noqa: E402
from tf_operator_tpu.serve.worker import RUNNERS, build_runner  # noqa: E402


def bench_environment() -> dict:
    """bench.py-style environment fingerprint; jax facts only when the
    runner actually loaded jax (the fake runner must stay importable on
    the slim install)."""
    import platform as _plat

    env = {"python": _plat.python_version()}
    if "jax" in sys.modules:
        import jax

        d = jax.devices()[0]
        env.update({"jax_version": jax.__version__,
                    "platform": d.platform,
                    "chip_kind": getattr(d, "device_kind", "")})
    return env


def config_fingerprint(config: dict) -> str:
    import hashlib

    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def run_bench(args) -> dict:
    rng = random.Random(args.seed)
    runner = build_runner(args.runner, args.slots)
    tenants = [f"tenant-{i}" for i in range(args.tenants)]
    # Staircase weights (1, 2, 3, ...): fairness under asymmetric quota,
    # like ClusterQueue nominal chips would render them.
    weights = {t: i + 1 for i, t in enumerate(tenants)}
    queue = RequestQueue(max_depth=args.max_queue, tenant_weights=weights)
    engine = ServingEngine(queue, ContinuousBatcher(runner))

    metrics.REGISTRY.reset()
    interval = 1.0 / args.qps if args.qps > 0 else 0.0
    next_arrival = time.monotonic()
    submitted = rejected = 0
    queue_depth_max = 0
    t0 = time.monotonic()
    while submitted + rejected < args.requests or not engine.idle:
        now = time.monotonic()
        while (submitted + rejected < args.requests
               and now >= next_arrival):
            i = submitted + rejected
            prompt_len = 1 + rng.randrange(args.max_prompt)
            request = Request(
                id=f"r{i:06d}", tenant=tenants[i % len(tenants)],
                prompt=[rng.randrange(200) for _ in range(prompt_len)],
                max_new_tokens=args.max_new_tokens)
            if queue.submit(request):
                submitted += 1
            else:
                rejected += 1
            # Open-loop arrivals with +-50% jitter around 1/qps.
            next_arrival += interval * (0.5 + rng.random())
        queue_depth_max = max(queue_depth_max, queue.depth())
        engine.step()
        if engine.idle and submitted + rejected < args.requests:
            sleep = max(0.0, next_arrival - time.monotonic())
            if sleep:
                time.sleep(min(sleep, 0.005))
    elapsed = time.monotonic() - t0

    p50 = metrics.serving_ttft_seconds.quantile(0.5)
    p99 = metrics.serving_ttft_seconds.quantile(0.99)
    config = {"runner": args.runner, "slots": args.slots,
              "qps": args.qps, "requests": args.requests,
              "tenants": args.tenants, "max_queue": args.max_queue,
              "max_prompt": args.max_prompt,
              "max_new_tokens": args.max_new_tokens, "seed": args.seed}
    label = "fake" if args.runner == "fake" else f"{args.runner}-tiny"
    return {
        "metric": f"serving_tokens_per_sec[{label}]",
        "value": round(engine.tokens_total / elapsed, 2) if elapsed else 0.0,
        "unit": "tokens/sec",
        "qps": round(engine.completed_total / elapsed, 2) if elapsed else 0.0,
        "ttft_p50_s": round(p50, 6) if p50 is not None else None,
        "ttft_p99_s": round(p99, 6) if p99 is not None else None,
        "queue_depth_max": queue_depth_max,
        "requests": args.requests,
        "completed": engine.completed_total,
        "rejected": rejected,
        "elapsed_s": round(elapsed, 3),
        "env": bench_environment(),
        "config_fingerprint": config_fingerprint(config),
    }


# --- diurnal scenario (gateway + autoscaler vs static peak) ------------

NAMESPACE = "bench"
JOB = "bench-serving"


def _diurnal_qps(t: float, period: float, peak_qps: float,
                 trough_qps: float, peak_fraction: float) -> float:
    """Square-ish diurnal trace: the first ``peak_fraction`` of every
    period is the burst, the rest the trough."""
    return peak_qps if (t % period) < peak_fraction * period else trough_qps


class _FleetSim(threading.Thread):
    """Rate-based serving-fleet simulator over a real spool.

    Serves ``per_slice_rate`` requests/second per slice the job
    currently holds (read live from the store, so resizes take effect
    the moment the spec lands), completing pending/ files oldest-first
    into done/ and observing each request's wait into the REAL
    serving_ttft_seconds histogram — the autoscaler's TTFT-burn signal
    measures genuine queueing delay. While a resize is settling
    (SliceGroup.status.resizing_reason set) the fleet serves NOTHING
    for ``settle_seconds`` — the world-restart cost elasticity pays —
    then clears the marker like the engine finishing the restart.
    Chips are held throughout (a restarting gang still owns its
    slices), so the chip-seconds integral charges the resize window.
    """

    def __init__(self, store, spool_root: str, per_slice_rate: float,
                 chips_per_slice: int, settle_seconds: float,
                 tick: float = 0.005):
        super().__init__(name="fleet-sim", daemon=True)
        self.store = store
        self.pending = os.path.join(spool_root, "pending")
        self.done = os.path.join(spool_root, "done")
        self.per_slice_rate = per_slice_rate
        self.chips_per_slice = chips_per_slice
        self.settle_seconds = settle_seconds
        self.tick = tick
        self.chip_seconds = 0.0
        self.served = 0
        self.slices_max_seen = 0
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=10)

    def _slices(self) -> int:
        job = self.store.try_get(store_mod.TPUJOBS, NAMESPACE, JOB)
        return job.spec.slice.num_slices if job is not None else 0

    def _settling(self) -> bool:
        group = self.store.try_get(store_mod.SLICEGROUPS, NAMESPACE, JOB)
        return group is not None and bool(group.status.resizing_reason)

    def _finish_settle(self) -> None:
        def clear(group):
            group.status.resizing_reason = ""

        retry_mod.update_with_conflict_retry(
            self.store, store_mod.SLICEGROUPS, NAMESPACE, JOB, clear,
            status=True, component="bench.fleet")

    def _serve_one(self) -> bool:
        oldest, oldest_mtime = None, None
        try:
            for n in os.listdir(self.pending):
                if not n.endswith(".json"):
                    continue
                p = os.path.join(self.pending, n)
                try:
                    m = os.path.getmtime(p)
                except OSError:
                    continue
                if oldest_mtime is None or m < oldest_mtime:
                    oldest, oldest_mtime = p, m
        except OSError:
            return False
        if oldest is None:
            return False
        try:
            with open(oldest, encoding="utf-8") as f:
                req = json.load(f)
            os.unlink(oldest)  # claim (exclusive: single fleet thread)
        except (OSError, ValueError):
            return False
        wait = max(0.0, time.time() - oldest_mtime)
        metrics.serving_ttft_seconds.observe(wait)
        out = {"id": req["id"], "tenant": req.get("tenant", "default"),
               "tokens": [t % 251 for t in
                          range(int(req.get("maxNewTokens", 1)))],
               "servedBy": "fleet-sim", "ttftSeconds": round(wait, 6)}
        path = os.path.join(self.done, req["id"] + ".json")
        with open(path + ".tmp", "w", encoding="utf-8") as f:
            json.dump(out, f)
        os.replace(path + ".tmp", path)
        self.served += 1
        return True

    def run(self) -> None:
        credit = 0.0
        last = time.monotonic()
        while not self._halt.is_set():
            time.sleep(self.tick)
            now = time.monotonic()
            dt, last = now - last, now
            slices = self._slices()
            self.slices_max_seen = max(self.slices_max_seen, slices)
            self.chip_seconds += slices * self.chips_per_slice * dt
            if self._settling():
                # World restart: chips held, nothing served, queue
                # grows — then the new world comes up.
                until = now + self.settle_seconds
                while (not self._halt.is_set()
                       and time.monotonic() < until):
                    time.sleep(self.tick)
                settled = time.monotonic()
                self.chip_seconds += (self._slices()
                                      * self.chips_per_slice
                                      * (settled - last))
                last = settled
                credit = 0.0
                self._finish_settle()
                continue
            credit = min(credit + slices * self.per_slice_rate * dt,
                         slices * self.per_slice_rate)  # no credit bank
            while credit >= 1.0 and self._serve_one():
                credit -= 1.0


def _gateway_post(url: str, payload: dict, results: dict,
                  lock: threading.Lock) -> None:
    data = json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()  # consume the full NDJSON stream
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    except Exception:
        code = -1
    with lock:
        results[code] = results.get(code, 0) + 1


def _diurnal_once(autoscale: bool, args) -> dict:
    """One full trace through gateway + spool + fleet; autoscale=False
    pins the gang at the peak slice count (the static baseline)."""
    from tf_operator_tpu import testutil
    from tf_operator_tpu.api.defaults import set_defaults
    from tf_operator_tpu.api.types import (
        ServingPolicy,
        SliceGroup,
        SliceGroupSpec,
        SliceGroupStatus,
        TPUSliceSpec,
    )
    from tf_operator_tpu.controller.autoscaler import ServingAutoscaler
    from tf_operator_tpu.controller.gang import (
        PHASE_RUNNING,
        SliceGangScheduler,
    )
    from tf_operator_tpu.runtime.store import Store
    from tf_operator_tpu.serve.gateway import GatewayServer

    metrics.REGISTRY.reset()
    rng = random.Random(args.seed)
    spool = tempfile.mkdtemp(prefix="bench-diurnal-")
    peak_slices = max(1, math.ceil(args.peak_qps / args.per_slice_rate))
    chips_per_slice = 4

    store = Store()
    job = testutil.new_tpujob(worker=0, name=JOB, namespace=NAMESPACE)
    job.spec.slice.accelerator = f"v5e-{chips_per_slice}"
    job.spec.slice.num_slices = 1 if autoscale else peak_slices
    job.spec.slice.min_slices = 1
    job.spec.slice.max_slices = peak_slices
    job.spec.run_policy.serving_policy = ServingPolicy(
        enabled=True, spool_directory=spool,
        max_queue_depth=args.max_queue,
        ttft_p99_slo_seconds=args.ttft_slo,
        target_queue_depth_per_slice=args.target_depth_per_slice,
        scale_down_cooldown_seconds=args.cooldown)
    set_defaults(job)
    store.create(store_mod.TPUJOBS, job)
    group = SliceGroup(
        spec=SliceGroupSpec(
            min_member=job.spec.slice.num_slices,
            slice=TPUSliceSpec(accelerator=job.spec.slice.accelerator,
                               num_slices=job.spec.slice.num_slices,
                               min_slices=1, max_slices=peak_slices)),
        status=SliceGroupStatus(phase=PHASE_RUNNING))
    group.metadata.name = JOB
    group.metadata.namespace = NAMESPACE
    store.create(store_mod.SLICEGROUPS, group)

    autoscaler = None
    if autoscale:
        autoscaler = ServingAutoscaler(
            store, None, namespace=NAMESPACE,
            interval_seconds=args.autoscale_interval)
        gang = SliceGangScheduler(store, elastic=True,
                                  resize_signals=autoscaler.signals)
        autoscaler.gang = gang

    fleet = _FleetSim(store, spool, per_slice_rate=args.per_slice_rate,
                      chips_per_slice=chips_per_slice,
                      settle_seconds=args.settle_seconds)
    gateway = GatewayServer(spool, port=0, max_queue_depth=args.max_queue,
                            timeout_seconds=30.0)
    gateway.start()
    fleet.start()
    if autoscaler is not None:
        autoscaler.start()

    url = f"http://127.0.0.1:{gateway.port}/v1/generate"
    results: dict = {}
    lock = threading.Lock()
    clients = []
    duration = args.periods * args.period
    t0 = time.monotonic()
    submitted = 0
    try:
        while True:
            t = time.monotonic() - t0
            if t >= duration:
                break
            qps = _diurnal_qps(t, args.period, args.peak_qps,
                               args.trough_qps, args.peak_fraction)
            prompt_len = 1 + rng.randrange(args.max_prompt)
            payload = {"prompt": [rng.randrange(200)
                                  for _ in range(prompt_len)],
                       "maxNewTokens": args.max_new_tokens}
            c = threading.Thread(target=_gateway_post,
                                 args=(url, payload, results, lock),
                                 daemon=True)
            c.start()
            clients.append(c)
            submitted += 1
            # Open-loop arrivals with +-50% jitter around 1/qps.
            time.sleep((1.0 / qps) * (0.5 + rng.random()))
        for c in clients:
            c.join(timeout=60)
        elapsed = time.monotonic() - t0
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        fleet.stop()
        gateway.stop()
        store.stop_watchers()

    grow = metrics.gang_resizes.value(direction="grow", reason="autoscale")
    shrink = metrics.gang_resizes.value(direction="shrink",
                                        reason="autoscale")
    p99 = metrics.serving_ttft_seconds.quantile(0.99)
    completed = results.get(200, 0)
    return {
        "submitted": submitted,
        "completed": completed,
        "rejected_429": results.get(429, 0),
        "dropped": submitted - completed - results.get(429, 0),
        "chip_seconds": round(fleet.chip_seconds, 3),
        "slices_peak": peak_slices,
        "slices_max_seen": fleet.slices_max_seen,
        "ttft_p99_s": round(p99, 6) if p99 is not None else None,
        "resizes_grow": int(grow),
        "resizes_shrink": int(shrink),
        "elapsed_s": round(elapsed, 3),
    }


def run_diurnal(args) -> dict:
    auto = _diurnal_once(True, args)
    static = _diurnal_once(False, args)
    saved = (1.0 - auto["chip_seconds"] / static["chip_seconds"]
             if static["chip_seconds"] else 0.0)
    slo_met = (auto["ttft_p99_s"] is not None
               and auto["ttft_p99_s"] <= args.ttft_slo)
    config = {"scenario": "diurnal", "period": args.period,
              "periods": args.periods, "peak_qps": args.peak_qps,
              "trough_qps": args.trough_qps,
              "peak_fraction": args.peak_fraction,
              "per_slice_rate": args.per_slice_rate,
              "settle_seconds": args.settle_seconds,
              "target_depth_per_slice": args.target_depth_per_slice,
              "cooldown": args.cooldown, "ttft_slo": args.ttft_slo,
              "seed": args.seed}
    return {
        "metric": "serving_diurnal_chip_seconds_saved",
        "value": round(saved * 100.0, 1),
        "unit": "percent",
        "slo_s": args.ttft_slo,
        "slo_met": slo_met,
        "autoscale": auto,
        "static": static,
        "env": bench_environment(),
        "config_fingerprint": config_fingerprint(config),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--scenario", default="throughput",
                        choices=("throughput", "diurnal"))
    parser.add_argument("--runner", default="fake",
                        choices=tuple(sorted(RUNNERS)))
    parser.add_argument("--requests", type=int, default=400)
    parser.add_argument("--qps", type=float, default=2000.0,
                        help="open-loop arrival rate (0 = submit "
                             "everything immediately)")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--tenants", type=int, default=3)
    parser.add_argument("--max-queue", type=int, default=256)
    parser.add_argument("--max-prompt", type=int, default=12)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    diurnal = parser.add_argument_group(
        "diurnal", "autoscaler-vs-static-peak scenario knobs")
    diurnal.add_argument("--period", type=float, default=4.0,
                         help="diurnal period, seconds")
    diurnal.add_argument("--periods", type=int, default=2)
    diurnal.add_argument("--peak-qps", type=float, default=60.0)
    diurnal.add_argument("--trough-qps", type=float, default=5.0)
    diurnal.add_argument("--peak-fraction", type=float, default=0.3,
                         help="fraction of each period at peak load")
    diurnal.add_argument("--per-slice-rate", type=float, default=25.0,
                         help="fleet service rate per slice, req/s")
    diurnal.add_argument("--settle-seconds", type=float, default=0.15,
                         help="world-restart pause per applied resize")
    diurnal.add_argument("--target-depth-per-slice", type=int, default=4)
    diurnal.add_argument("--cooldown", type=float, default=0.4,
                         help="scaleDownCooldownSeconds for the run")
    diurnal.add_argument("--ttft-slo", type=float, default=1.5)
    diurnal.add_argument("--autoscale-interval", type=float, default=0.05)
    args = parser.parse_args(argv)
    try:
        if args.scenario == "diurnal":
            print(json.dumps(run_diurnal(args)))
        else:
            print(json.dumps(run_bench(args)))
        return 0
    except Exception as e:  # one JSON line, even on failure
        print(json.dumps({
            "metric": ("serving_diurnal_chip_seconds_saved"
                       if args.scenario == "diurnal"
                       else "serving_tokens_per_sec"),
            "value": 0.0,
            "unit": ("percent" if args.scenario == "diurnal"
                     else "tokens/sec"),
            "error": f"{type(e).__name__}: {e}",
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
