"""Benchmark: serving-plane throughput and tail latency.

Drives a synthetic request load through the in-process ServingEngine
(request queue -> continuous batcher -> runner) and prints exactly ONE
JSON line, bench.py conventions:

    {"metric": "serving_tokens_per_sec[fake|llama-tiny]", "value": N,
     "unit": "tokens/sec", "qps": ..., "ttft_p50_s": ..., "ttft_p99_s":
     ..., "queue_depth_max": ..., "requests": ..., "completed": ...,
     "rejected": ..., "env": {...}, "config_fingerprint": "..."}

The p50/p99 TTFT come from the serving_ttft_seconds histogram via
Histogram.quantile (runtime/metrics.py) — the same numbers a scrape +
histogram_quantile() would produce. Arrivals are open-loop at --qps
(deterministic inter-arrival jitter off a seed), split across --tenants
weighted lanes, so queue_depth_max reflects genuine burst backpressure
rather than lock-step submission.

Runner "fake" is the deterministic jax-free generator (tier-1 smoke,
pinned by tests/test_bench_serving.py); "llama" runs the real
incremental-decode path on a tiny model.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

REPO = __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tf_operator_tpu.runtime import metrics  # noqa: E402
from tf_operator_tpu.serve.batcher import (  # noqa: E402
    ContinuousBatcher,
    FakeRunner,
)
from tf_operator_tpu.serve.engine import ServingEngine  # noqa: E402
from tf_operator_tpu.serve.queue import Request, RequestQueue  # noqa: E402


def build_runner(kind: str, slots: int):
    if kind == "fake":
        return FakeRunner(max_slots=slots)
    from tf_operator_tpu.serve.runner import LlamaRunner

    return LlamaRunner(max_slots=slots)


def bench_environment() -> dict:
    """bench.py-style environment fingerprint; jax facts only when the
    runner actually loaded jax (the fake runner must stay importable on
    the slim install)."""
    import platform as _plat

    env = {"python": _plat.python_version()}
    if "jax" in sys.modules:
        import jax

        d = jax.devices()[0]
        env.update({"jax_version": jax.__version__,
                    "platform": d.platform,
                    "chip_kind": getattr(d, "device_kind", "")})
    return env


def config_fingerprint(config: dict) -> str:
    import hashlib

    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def run_bench(args) -> dict:
    rng = random.Random(args.seed)
    runner = build_runner(args.runner, args.slots)
    tenants = [f"tenant-{i}" for i in range(args.tenants)]
    # Staircase weights (1, 2, 3, ...): fairness under asymmetric quota,
    # like ClusterQueue nominal chips would render them.
    weights = {t: i + 1 for i, t in enumerate(tenants)}
    queue = RequestQueue(max_depth=args.max_queue, tenant_weights=weights)
    engine = ServingEngine(queue, ContinuousBatcher(runner))

    metrics.REGISTRY.reset()
    interval = 1.0 / args.qps if args.qps > 0 else 0.0
    next_arrival = time.monotonic()
    submitted = rejected = 0
    queue_depth_max = 0
    t0 = time.monotonic()
    while submitted + rejected < args.requests or not engine.idle:
        now = time.monotonic()
        while (submitted + rejected < args.requests
               and now >= next_arrival):
            i = submitted + rejected
            prompt_len = 1 + rng.randrange(args.max_prompt)
            request = Request(
                id=f"r{i:06d}", tenant=tenants[i % len(tenants)],
                prompt=[rng.randrange(200) for _ in range(prompt_len)],
                max_new_tokens=args.max_new_tokens)
            if queue.submit(request):
                submitted += 1
            else:
                rejected += 1
            # Open-loop arrivals with +-50% jitter around 1/qps.
            next_arrival += interval * (0.5 + rng.random())
        queue_depth_max = max(queue_depth_max, queue.depth())
        engine.step()
        if engine.idle and submitted + rejected < args.requests:
            sleep = max(0.0, next_arrival - time.monotonic())
            if sleep:
                time.sleep(min(sleep, 0.005))
    elapsed = time.monotonic() - t0

    p50 = metrics.serving_ttft_seconds.quantile(0.5)
    p99 = metrics.serving_ttft_seconds.quantile(0.99)
    config = {"runner": args.runner, "slots": args.slots,
              "qps": args.qps, "requests": args.requests,
              "tenants": args.tenants, "max_queue": args.max_queue,
              "max_prompt": args.max_prompt,
              "max_new_tokens": args.max_new_tokens, "seed": args.seed}
    label = "fake" if args.runner == "fake" else "llama-tiny"
    return {
        "metric": f"serving_tokens_per_sec[{label}]",
        "value": round(engine.tokens_total / elapsed, 2) if elapsed else 0.0,
        "unit": "tokens/sec",
        "qps": round(engine.completed_total / elapsed, 2) if elapsed else 0.0,
        "ttft_p50_s": round(p50, 6) if p50 is not None else None,
        "ttft_p99_s": round(p99, 6) if p99 is not None else None,
        "queue_depth_max": queue_depth_max,
        "requests": args.requests,
        "completed": engine.completed_total,
        "rejected": rejected,
        "elapsed_s": round(elapsed, 3),
        "env": bench_environment(),
        "config_fingerprint": config_fingerprint(config),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--runner", default="fake",
                        choices=("fake", "llama"))
    parser.add_argument("--requests", type=int, default=400)
    parser.add_argument("--qps", type=float, default=2000.0,
                        help="open-loop arrival rate (0 = submit "
                             "everything immediately)")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--tenants", type=int, default=3)
    parser.add_argument("--max-queue", type=int, default=256)
    parser.add_argument("--max-prompt", type=int, default=12)
    parser.add_argument("--max-new-tokens", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    try:
        print(json.dumps(run_bench(args)))
        return 0
    except Exception as e:  # one JSON line, even on failure
        print(json.dumps({
            "metric": "serving_tokens_per_sec",
            "value": 0.0,
            "unit": "tokens/sec",
            "error": f"{type(e).__name__}: {e}",
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
