"""Flash-attention tile-size sweep (round-6 satellite; VERDICT round-5
"Next round" #5 groundwork).

The round-5 roofline attributes ~100 ms/step of the Llama budget to the
flash kernel running at ~35% of peak and calls that "kernel-structural
at S=2048" — on the evidence of a single round-2 sweep that only tried
128-square blocks against the 512/1024 defaults. This tool produces the
full measured grid: fwd and fwd+bwd de-drifted timings for every
(block_q, block_k) tiling that divides the shape, plus the XLA
reference attention row, so the structural claim (or a better default)
rests on a table instead of a memory.

    python benchmarks/sweep_flash.py [--seq 2048] [--batch 8]
        [--blocks-q 128,256,512,1024,2048] [--blocks-k ...]

Off-TPU the kernel only runs in interpret mode (orders of magnitude
slow): pass --interpret with a small --seq to smoke the harness; timing
rows are labeled with the platform so interpreted numbers can never be
mistaken for kernel measurements.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import timing  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="GQA KV heads (default = --heads, MHA)")
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--blocks-q", default="128,256,512,1024,2048")
    ap.add_argument("--blocks-k", default="128,256,512,1024,2048")
    ap.add_argument("--interpret", action="store_true",
                    help="run the pallas kernel in interpret mode "
                         "(off-TPU smoke; NOT a measurement)")
    ap.add_argument("--fwd-only", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.flash_attention import (
        flash_attention,
        flash_supported,
        on_tpu,
    )
    from tf_operator_tpu.ops.layers import attention, repeat_kv

    if not on_tpu() and not args.interpret:
        print("no TPU: pass --interpret (with a small --seq) to smoke "
              "the harness in interpret mode", file=sys.stderr)
        return 1

    b, s, h, d = args.batch, args.seq, args.heads, args.head_dim
    h_kv = args.kv_heads or h
    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if on_tpu() else jnp.float32
    kq, kk, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, h_kv, d), dtype)
    v = jax.random.normal(kv_, (b, s, h_kv, d), dtype)

    def time_fn(fn):
        fwd = jax.jit(fn)
        row = {"fwd_ms": round(timing.timed(fwd, q, k, v) * 1e3, 2)}
        if not args.fwd_only:
            grad = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                argnums=(0, 1, 2)))
            row["fwd_bwd_ms"] = round(
                timing.timed(grad, q, k, v) * 1e3, 2)
        return row

    # XLA reference row (repeats KV to full heads itself for GQA)
    def xla_ref(q, k, v):
        group = q.shape[2] // k.shape[2]
        if group > 1:
            k, v = repeat_kv(k, group), repeat_kv(v, group)
        return attention(q, k, v, causal=True)

    base = {"batch": b, "seq": s, "heads": h, "kv_heads": h_kv,
            "head_dim": d, "platform": platform,
            "interpret": bool(args.interpret and not on_tpu())}
    print(json.dumps({**base, "impl": "xla_reference", **time_fn(xla_ref)}),
          flush=True)

    for bq in (int(x) for x in args.blocks_q.split(",")):
        for bk in (int(x) for x in args.blocks_k.split(",")):
            if not flash_supported(s, s, d, bq, bk):
                continue

            def flash(q, k, v, bq=bq, bk=bk):
                return flash_attention(q, k, v, causal=True, block_q=bq,
                                       block_k=bk,
                                       interpret=not on_tpu())

            print(json.dumps({**base, "impl": "flash", "block_q": bq,
                              "block_k": bk, **time_fn(flash)}),
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
