"""Pipeline-parallel schedule benchmark (round-2 verdict item #6).

Two measurement modes:

- default (real chip or whatever jax.devices() offers, single device):
  schedule OVERHEAD — the 1F1B fused scan vs a plain fused
  loss+grad step on the same stage stack at pp=1, across microbatch
  counts. Quantifies what the scan/masking machinery costs when no
  pipelining is actually needed.
- ``--cpu-mesh``: 8 virtual CPU devices; step-time vs microbatch count
  for pp in {2,4,8}, validating the bubble model — 1F1B runs
  m + 2(pp-1) ticks, so per-microbatch time should scale like
  (m + 2(pp-1))/m — and comparing against the GPipe+autodiff path.
  Also reports XLA's compiled temp-buffer sizes, which show the O(m)
  (GPipe scan residuals) vs O(pp) (1F1B ring) activation-memory
  separation.

    python benchmarks/bench_pipeline.py                # chip overhead
    python benchmarks/bench_pipeline.py --cpu-mesh     # schedule curves
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from timing import setup as _setup, timed  # noqa: E402


def make_stage(hid, mlp, dtype):
    import jax

    def stage_fn(params, x):
        h = jax.nn.gelu(x.astype(dtype) @ params["w1"])
        return x + (h @ params["w2"]).astype(x.dtype)

    def init(key, n_stages):
        import jax.numpy as jnp

        ks = jax.random.split(key, 2 * n_stages)
        per = [{"w1": (jax.random.normal(ks[2 * i], (hid, mlp)) * 0.02
                       ).astype(dtype),
                "w2": (jax.random.normal(ks[2 * i + 1], (mlp, hid)) * 0.02
                       ).astype(dtype)}
               for i in range(n_stages)]
        return per

    return stage_fn, init


def run_cpu_mesh():
    import jax.numpy as jnp

    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
    from tf_operator_tpu.parallel.pipeline import (
        pipeline_sharded,
        pipeline_train_sharded,
        stack_stage_params,
    )

    hid, mlp, batch = 256, 1024, 64
    stage_fn, init = make_stage(hid, mlp, jnp.float32)

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    for pp in (2, 4, 8):
        mesh = make_mesh(MeshConfig(dp=1, pp=pp),
                         devices=jax.devices()[:pp])
        stacked = stack_stage_params(init(jax.random.PRNGKey(0), pp))
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, hid))
        tgt = jnp.zeros_like(x)
        rows = []
        for m in (2, 4, 8, 16, 32):
            if batch % m:
                continue

            @jax.jit
            def train_1f1b(p, x, t, m=m):
                return pipeline_train_sharded(stage_fn, loss_fn, p, x, t,
                                              mesh, num_microbatches=m)

            @jax.jit
            def train_gpipe(p, x, t, m=m):
                def loss(p):
                    y = pipeline_sharded(stage_fn, p, x, mesh,
                                         num_microbatches=m)
                    return loss_fn(y, t)

                return jax.value_and_grad(loss)(p)

            t_1f1b = timed(train_1f1b, stacked, x, tgt)
            t_gpipe = timed(train_gpipe, stacked, x, tgt)
            lowered = train_1f1b.lower(stacked, x, tgt).compile()
            lowered_g = train_gpipe.lower(stacked, x, tgt).compile()

            def temp_bytes(c):
                try:
                    ma = c.memory_analysis()
                    return int(ma.temp_size_in_bytes)
                except Exception:
                    return -1

            from tf_operator_tpu.parallel.pipeline import (
                compiled_peak_bytes,
                select_schedule,
            )

            # Peak metric: the SAME formula the trainer's auto probe
            # uses (compiled_peak_bytes) — these columns must describe
            # what schedule="auto" actually picks.
            pg = compiled_peak_bytes(lowered_g)
            pf = compiled_peak_bytes(lowered)
            chosen_ample = select_schedule(pg, 1 << 40)
            # A budget between the two footprints is the memory-bound
            # regime 1F1B exists for — only meaningful when GPipe's
            # peak actually exceeds 1F1B's (at tiny m the 2pp-slot ring
            # can out-size GPipe's stash).
            if pg is not None and pf is not None and pg > pf:
                chosen_tight = select_schedule(pg, (pf + pg) // 2)
            else:
                chosen_tight = "n/a"
            times = {"gpipe": t_gpipe, "1f1b": t_1f1b}
            rows.append({
                "pp": pp, "m": m,
                "t_1f1b_ms": round(t_1f1b * 1e3, 2),
                "t_gpipe_ms": round(t_gpipe * 1e3, 2),
                "model_ticks_1f1b": m + 2 * (pp - 1),
                "model_ticks_gpipe_fwd": m + pp - 1,
                "temp_mb_1f1b": round(temp_bytes(lowered) / 2**20, 1),
                "temp_mb_gpipe": round(temp_bytes(lowered_g) / 2**20, 1),
                "auto_choice": chosen_ample,
                # The verdict's bar: the chosen schedule is never the
                # slower of the two that FIT. Under the tight budget
                # only 1F1B fits, so it is vacuously optimal there.
                "auto_is_fastest": (times[chosen_ample]
                                    <= min(times.values()) + 1e-9),
                "auto_choice_tight_budget": chosen_tight,
            })
        for r in rows:
            print(json.dumps(r), flush=True)
        # Bubble-model fit: per-tick time from the largest-m row.
        if len(rows) >= 2:
            r = rows[-1]
            per_tick = r["t_1f1b_ms"] / r["model_ticks_1f1b"]
            print(json.dumps({
                "pp": pp, "per_tick_ms": round(per_tick, 3),
                "bubble_frac_m8": round(2 * (pp - 1) / (8 + 2 * (pp - 1)), 3),
                "bubble_frac_m32": round(2 * (pp - 1) / (32 + 2 * (pp - 1)),
                                         3),
            }), flush=True)


def run_chip_overhead():
    import jax.numpy as jnp

    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
    from tf_operator_tpu.parallel.pipeline import (
        pipeline_train_sharded,
        stack_stage_params,
    )

    # Big enough that per-call time dominates two-point timing noise.
    hid, mlp, batch = 4096, 16384, 256
    stage_fn, init = make_stage(hid, mlp, jnp.bfloat16)

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    mesh = make_mesh(MeshConfig(dp=1, pp=1), devices=jax.devices()[:1])
    stacked = stack_stage_params(init(jax.random.PRNGKey(0), 1))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, hid),
                          jnp.bfloat16)
    tgt = jnp.zeros_like(x)

    @jax.jit
    def plain(p, x, t):
        def loss(p):
            local = jax.tree_util.tree_map(lambda q: q[0], p)
            return loss_fn(stage_fn(local, x), t)

        return jax.value_and_grad(loss)(p)

    t_plain = timed(plain, stacked, x, tgt)
    print(json.dumps({"pp": 1, "mode": "plain_fused",
                      "t_ms": round(t_plain * 1e3, 3)}), flush=True)

    ms, ts = [], []
    for m in (1, 2, 4, 8):
        @jax.jit
        def train(p, x, t, m=m):
            return pipeline_train_sharded(stage_fn, loss_fn, p, x, t,
                                          mesh, num_microbatches=m)

        t_1f1b = timed(train, stacked, x, tgt)
        ms.append(m)
        ts.append(t_1f1b)
        print(json.dumps({
            "pp": 1, "mode": "1f1b", "m": m,
            "t_ms": round(t_1f1b * 1e3, 3),
        }), flush=True)
    # Total work is constant across m (fixed global batch), so the
    # slope of t(m) is the per-tick schedule overhead on this platform.
    n = len(ms)
    mean_m, mean_t = sum(ms) / n, sum(ts) / n
    slope = (sum((a - mean_m) * (b - mean_t) for a, b in zip(ms, ts))
             / sum((a - mean_m) ** 2 for a in ms))
    print(json.dumps({
        "pp": 1, "mode": "fit",
        "per_tick_overhead_ms": round(slope * 1e3, 3),
        "note": "t(m) slope at constant total work = per-tick schedule "
                "cost (dispatch/masking); amortized by larger "
                "microbatches on real multi-stage meshes",
    }), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu-mesh", action="store_true")
    args = ap.parse_args()
    jax = _setup(args.cpu_mesh)
    if args.cpu_mesh:
        run_cpu_mesh()
    else:
        run_chip_overhead()
    sys.exit(0)
