"""Full-step XLA profile of the Mixtral MoE training step (round-6
roofline; VERDICT round-5 "Next round" #1).

Round 5 measured the 512M MoE at 19,850 tok/s = 15.1% active-param MFU
against the dense decoder's 47% and *explained* the gap in prose
(dispatch einsums, capacity factor, router) without profiling it. This
tool captures the exact ``bench_moe`` training step under
``jax.profiler.trace`` (same methodology as ``profile_llama.py``) and
aggregates:

- the generic per-HLO-category step budget (``profile_step.parse_trace``);
- an MoE bucket table — expert FFN einsums, dispatch/combine routing,
  router/top-k/aux, optimizer+elementwise, attention — classified from
  fusion operand shapes (best-effort; the residual is reported as
  ``unattributed``, never silently spread);
- the *analytic* dispatch budget for the profiled config: one-hot
  dispatch/combine einsum FLOPs, routing-tensor bytes, and expert-FFN
  FLOPs per step, computed exactly from the shapes — the structural
  part of the roofline that holds whatever the fusion boundaries do.

``--dispatch gather`` profiles the sort/gather routing path for the
A/B. On a host without the chip the trace carries op times but no
bytes/FLOP counters (CPU fallback in ``parse_trace``); the artifact
schema is identical so tier-1 smoke-pins it (tests/test_bench_moe.py).

Usage:
    python benchmarks/profile_moe.py [--steps 4] [--dispatch einsum]
        [--preset 512m|tiny] [--out results.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_moe import (  # noqa: E402
    active_param_count,
    build_moe_step,
    moe_step_flops,
)
from profile_step import parse_trace  # noqa: E402  (stdlib-only parser)

MOE_BUCKETS = ("expert_ffn", "dispatch_combine", "router_topk_aux",
               "attention", "optimizer_elementwise", "unattributed")


def _capacity(cfg, batch: int, seq: int) -> int:
    t = batch * seq
    return max(cfg.experts_per_token,
               int(t * cfg.experts_per_token * cfg.capacity_factor
                   / cfg.n_experts))


def analytic_dispatch_budget(cfg, batch: int, seq: int,
                             nparams: int) -> dict:
    """Exact per-step byte/FLOP budget of the routing machinery — the
    structural half of the roofline, independent of fusion boundaries.

    einsum path: dispatch ("tec,th->ech") and combine ("tec,ech->th")
    each execute 2·T·E·C·H FLOPs forward; backward re-runs the combine
    contraction twice (d_combine and d_expert_out) and the dispatch
    contraction once (d_x; the one-hot dispatch tensor itself is
    integer-derived, no cotangent), so 5 such contractions per layer
    per step before remat. The [T,E,C] routing tensors cost
    2·T·E·C·itemsize bytes per layer to materialize.

    gather path: the same permutation moves only 2·(E·C·H + T·K·H)
    buffer bytes per direction and O(T·K·log T·K) sort keys — FLOPs ~0.
    """
    t = batch * seq
    e, k, h = cfg.n_experts, cfg.experts_per_token, cfg.hidden
    c = _capacity(cfg, batch, seq)
    m = cfg.mlp_dim
    item = 2 if cfg.dtype.__name__ == "bfloat16" else 4
    contraction = 2.0 * t * e * c * h                 # one tec-einsum
    ffn_fwd = 3 * 2.0 * e * c * h * m                 # gate/up/down
    layers = cfg.n_layers
    return {
        "capacity": c,
        "dispatch_einsum_tflop_per_step_fwd": round(
            2 * contraction * layers / 1e12, 2),
        "dispatch_einsum_tflop_per_step_fwd_bwd": round(
            5 * contraction * layers / 1e12, 2),
        "routing_tensor_gb_per_layer": round(2 * t * e * c * item / 1e9, 2),
        "expert_ffn_tflop_per_step_fwd": round(ffn_fwd * layers / 1e12, 2),
        "gather_buffer_gb_per_layer": round(
            2 * (e * c * h + t * k * h) * item / 1e9, 3),
        "model_tflop_per_step": round(
            moe_step_flops(cfg, nparams, batch, seq) / 1e12, 2),
    }


def classify_moe(rows, cfg, batch: int, seq: int) -> list:
    """Best-effort bucket table from fusion operand shapes.

    Priority matters: expert-FFN einsums mention the [E,C,M] activation,
    dispatch/combine einsums the [T,E,C] one-hot tensors; the [E,C,H]
    buffer boundary alone is ambiguous and stays unattributed rather
    than guessed. Sorts split by width: the router's top-k sorts [T,E],
    the gather path's routing argsort runs at [T·K].
    """
    t = batch * seq
    e, k = cfg.n_experts, cfg.experts_per_token
    c = _capacity(cfg, batch, seq)
    m, h = cfg.mlp_dim, cfg.hidden
    sig_ffn = (f"{e},{c},{m}", f"{c},{m}", f"{m},{h}", f"{h},{m}")
    sig_disp = (f"{t},{e},{c}", f"{e},{c},{t}")
    sig_router = (f"{t},{e}]", f"{t},{e}}}")
    totals = {b: [0.0, 0.0, 0.0, 0.0] for b in MOE_BUCKETS}  # ms, pct, gb, tf

    def bucket(r) -> str:
        name = r["name"]
        ln = r.get("long", "") + " " + r.get("shape", "")
        if "flash" in name or "attention" in name:
            return "attention"
        if any(s in ln for s in sig_disp):
            return "dispatch_combine"
        if "sort" in name or "scatter" in name or "gather" in name:
            # gather-path routing runs at T·K width; router top-k at [T,E]
            if f"{t * k}" in ln:
                return "dispatch_combine"
            return "router_topk_aux"
        if any(s in ln for s in sig_ffn):
            return "expert_ffn"
        if any(s in ln for s in sig_router):
            return "router_topk_aux"
        if "adam" in name or "loop_fusion" in name:
            return "optimizer_elementwise"
        return "unattributed"

    for r in rows:
        g = totals[bucket(r)]
        g[0] += r["ms_per_step"]
        g[1] += r["pct"]
        g[2] += r["gbps"] * r["ms_per_step"] / 1e3        # GB moved
        g[3] += r["tflops"] * r["ms_per_step"] / 1e3      # TFLOP done
    out = []
    for b in MOE_BUCKETS:
        ms, pct, gb, tf = totals[b]
        out.append({
            "bucket": b,
            "ms_per_step": round(ms, 2),
            "pct": round(pct, 1),
            "gbps": round(gb / (ms / 1e3), 1) if ms else 0.0,
            "tflops": round(tf / (ms / 1e3), 2) if ms else 0.0,
        })
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--preset", default="512m", choices=("512m", "tiny"))
    ap.add_argument("--dispatch", default="einsum",
                    choices=("einsum", "gather"))
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax

    from bench import bench_config_fingerprint, bench_environment, detect_chip

    step, state, batch_d, cfg, ctx = build_moe_step(
        args.preset, args.batch, args.seq, args.dispatch)
    for _ in range(3):
        state, m = step(state, batch_d)
    float(m["loss"])  # host sync: block_until_ready lies on axon
    outdir = tempfile.mkdtemp(prefix="moe-profile-")
    with jax.profiler.trace(outdir):
        for _ in range(args.steps):
            state, m = step(state, batch_d)
        float(m["loss"])
    ctx.__exit__(None, None, None)
    traces = sorted(glob.glob(os.path.join(
        outdir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not traces:
        raise SystemExit(f"no trace produced under {outdir}")
    print(f"trace: {traces[-1]}", file=sys.stderr)

    summary = parse_trace(traces[-1], args.steps, top=None, with_long=True)
    summary["moe_buckets"] = classify_moe(summary["top_ops"], cfg,
                                          args.batch, args.seq)
    # Classification done: the artifact keeps the 20 biggest ops, sans
    # the long_name blobs.
    summary["top_ops"] = [
        {k: v for k, v in r.items() if k != "long"}
        for r in summary["top_ops"][:20]]

    B, S = args.batch, args.seq
    nparams = sum(x.size for x in jax.tree.leaves(state.params))
    model_tflop = moe_step_flops(cfg, nparams, B, S) / 1e12
    dev_s = summary["device_ms_per_step"] / 1e3
    summary["params"] = nparams
    summary["params_active"] = active_param_count(cfg, nparams)
    summary["nominal_tflop_per_step"] = round(model_tflop, 3)
    summary["nominal_mfu_active_pct"] = round(
        model_tflop / dev_s / args.peak_tflops * 100, 1) if dev_s else 0.0
    summary["tokens_per_sec_device"] = round(B * S / dev_s) if dev_s else 0
    summary["dispatch"] = args.dispatch
    summary["analytic"] = analytic_dispatch_budget(cfg, B, S, nparams)
    summary["batch_size"] = B
    config = {"preset": args.preset, "batch": B, "seq": S,
              "dispatch": args.dispatch, "steps": args.steps,
              "capacity_factor": cfg.capacity_factor,
              "n_experts": cfg.n_experts,
              "experts_per_token": cfg.experts_per_token}
    summary["config"] = config
    summary["env"] = bench_environment(detect_chip())
    summary["config_fingerprint"] = bench_config_fingerprint(config)
    out = json.dumps(summary, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)


if __name__ == "__main__":
    main()
