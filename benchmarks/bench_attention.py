"""Attention kernel benchmark: pallas flash vs XLA reference.

Produced the attention table in docs/benchmarks.md. Run on a TPU chip:
    python benchmarks/bench_attention.py [--seq 2048] [--batch 8]
Timing uses host-sync via float() (block_until_ready can return early
on tunneled PJRT plugins).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, n=10, warm=2):
    for _ in range(warm):
        out = fn(*args)
    float(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    float(out)
    return (time.perf_counter() - t0) / n


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--impl", default="both",
                    choices=["both", "xla", "flash"],
                    help="flash-only for long sequences (the XLA path "
                         "materializes S^2 scores and OOMs past ~8k)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.flash_attention import flash_attention
    from tf_operator_tpu.ops.layers import attention as xla_attention

    B, H, S, D = args.batch, args.heads, args.seq, args.head_dim
    peak = args.peak_tflops * 1e12
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (B, S, H, D), jnp.bfloat16) * 0.1
               for i in range(3))
    flops = 4 * B * H * S * S * D / 2  # causal

    impls = [("xla", xla_attention), ("flash", flash_attention)]
    if args.impl != "both":
        impls = [(n, f) for n, f in impls if n == args.impl]
    for name, fn in impls:
        fwd = jax.jit(lambda q, k, v, f=fn:
                      f(q, k, v, causal=True).astype(jnp.float32).sum())
        dt = timeit(fwd, q, k, v)
        print(json.dumps({"impl": name, "pass": "fwd",
                          "ms": round(dt * 1e3, 2),
                          "mfu": round(flops / dt / peak, 3)}))
        grad = jax.jit(lambda q, k, v, f=fn: sum(
            x.astype(jnp.float32).sum() for x in jax.grad(
                lambda q, k, v: f(q, k, v, causal=True)
                .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)))
        dt = timeit(grad, q, k, v)
        print(json.dumps({"impl": name, "pass": "fwd+bwd",
                          "ms": round(dt * 1e3, 2),
                          "mfu": round(3.5 * flops / dt / peak, 3)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
