"""Regenerate checked-in generated manifests (hack/update-codegen.sh
analog). tests/test_manifests.py is the verify-codegen analog: it fails
when the checked-in schema drifts from the API dataclasses."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu.api.schema import generate_schema  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "base", "tpujob.schema.json")

if __name__ == "__main__":
    with open(OUT, "w") as f:
        json.dump(generate_schema(), f, indent=2, sort_keys=False)
        f.write("\n")
    print(f"wrote {OUT}")
