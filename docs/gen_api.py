"""Generate docs/api.md from the API dataclasses (the reference's
generated docs/api/generated.asciidoc analog). Freshness enforced by
tests/test_manifests.py."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu.api.schema import generate_schema  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "api.md")

HEADER = """# TPUJob API reference

*Generated from the API dataclasses by `docs/gen_api.py` — do not edit.*

Wire format: camelCase JSON/YAML (K8s convention); machine-readable
schema at `manifests/base/tpujob.schema.json`. Semantic rules beyond
types (required containers, replica bounds, name formats) live in
`tf_operator_tpu/api/validation.py`. The TenantQueue/ClusterQueue
quota kinds (cohort semantics, borrowing, reclaim) are documented in
`docs/quota.md`; the CheckpointRecord kind (the save-before-evict
barrier's ack channel) in `docs/checkpoint.md`; the `serving` replica
role and ServingPolicy (online-inference gangs) in `docs/serving.md`;
the per-role RolePolicy (heterogeneous actor–learner gangs, the
`actor` replica type, disruption classes, elastic replica bands) in
`docs/rl.md`.
"""


def _fmt_type(prop: dict) -> str:
    if "$ref" in prop:
        name = prop["$ref"].rsplit("/", 1)[-1]
        return f"[{name}](#{name.lower()})"
    t = prop.get("type")
    if t == "array":
        return f"[]{_fmt_type(prop.get('items', {}))}"
    if t == "object" and "additionalProperties" in prop:
        return f"map[string]{_fmt_type(prop['additionalProperties'])}"
    if t == "string" and prop.get("format") == "date-time":
        return "string (RFC3339)"
    return t or "any"


def render() -> str:
    from tf_operator_tpu.api.types import (
        CheckpointRecord,
        ClusterQueue,
        TenantQueue,
    )

    lines = [HEADER]
    emitted = set()

    def emit(name: str, obj: dict):
        if name in emitted:
            return
        emitted.add(name)
        lines.append(f"\n## {name}\n")
        lines.append("| Field | Type |")
        lines.append("|---|---|")
        for field, prop in obj.get("properties", {}).items():
            lines.append(f"| `{field}` | {_fmt_type(prop)} |")

    # TPUJob first (the headline kind), then the tenant-queue admission
    # kinds and the checkpoint-coordination record; shared $defs
    # (ObjectMeta etc.) are emitted once.
    for cls in (None, TenantQueue, ClusterQueue, CheckpointRecord):
        schema = generate_schema(cls)
        emit(schema["title"], schema)
        for name, obj in schema.get("$defs", {}).items():
            emit(name, obj)
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    with open(OUT, "w") as f:
        f.write(render())
    print(f"wrote {OUT}")
