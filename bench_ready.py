"""Benchmark: pod-to-AllReplicasReady latency (BASELINE north-star #2).

Runs the full control loop hermetically — real controller, real store,
real subprocess pods running the worker stub — and measures the time
from job creation to the AllReplicasReady latch
(`status.all_replicas_ready_time`, observed by the controller into the
`tpu_operator_all_replicas_ready_seconds` histogram; see
tf_operator_tpu/controller/status.py).

Reference analog: the reference has no such benchmark (SURVEY §6); its
implicit SLO is the e2e wait budget (~10-15 min per job,
py/kubeflow/tf_operator/tf_job_client.py:116-210). Here a 1-chief +
4-worker gang (the ResNet-50 BASELINE topology) must reach
AllReplicasReady in well under a second of controller work.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "seconds", "vs_baseline": N}
vs_baseline = (reference implicit SLO lower bound, 600 s) / measured.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.sdk import TPUJobClient

REFERENCE_SLO_SECONDS = 600.0  # lower bound of the reference e2e wait budget


def make_job(name: str, stub_dir: str, workers: int, chief: int) -> TPUJob:
    def spec(n: int) -> ReplicaSpec:
        return ReplicaSpec(
            replicas=n,
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(
                name=constants.DEFAULT_CONTAINER_NAME,
                command=[sys.executable, "-m",
                         "tf_operator_tpu.runtime.worker_stub"],
                env={"TPUJOB_STUB_DIR": stub_dir},
            )])))

    replica_specs = {"worker": spec(workers)}
    if chief:
        replica_specs["chief"] = spec(chief)
    return TPUJob(metadata=ObjectMeta(name=name),
                  spec=TPUJobSpec(replica_specs=replica_specs))


def measure_once(trial: int, workers: int, chief: int) -> float:
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        with tempfile.TemporaryDirectory() as stub_dir:
            job = make_job(f"bench-ready-{trial}", stub_dir, workers, chief)
            t0 = time.monotonic()
            client.create(job)
            deadline = t0 + 120.0
            while time.monotonic() < deadline:
                got = client.get(job.metadata.name)
                if got and got.status.all_replicas_ready_time is not None:
                    dt = time.monotonic() - t0
                    client.delete(job.metadata.name)
                    return dt
                time.sleep(0.01)
        raise TimeoutError("AllReplicasReady never latched")
    finally:
        op.stop()


def main() -> int:
    workers, chief, trials = 4, 1, 3
    try:
        latencies = [measure_once(i, workers, chief) for i in range(trials)]
        best = min(latencies)
        print(json.dumps({
            "metric": f"pod_to_all_replicas_ready_seconds[{chief}c+{workers}w]",
            "value": round(best, 3),
            "unit": "seconds",
            "vs_baseline": round(REFERENCE_SLO_SECONDS / best, 1),
        }))
        return 0
    except Exception as e:
        print(json.dumps({
            "metric": "pod_to_all_replicas_ready_seconds",
            "value": 0.0, "unit": "seconds", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
