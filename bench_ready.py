"""Benchmark: pod-to-AllReplicasReady latency (BASELINE north-star #2).

Runs the full control loop hermetically — real controller, real store,
real subprocess pods running the worker stub — and measures the time
from job creation to the AllReplicasReady latch
(`status.all_replicas_ready_time`, observed by the controller into the
`tpu_operator_all_replicas_ready_seconds` histogram; see
tf_operator_tpu/controller/status.py).

Reference analog: the reference has no such benchmark (SURVEY §6); its
implicit SLO is the e2e wait budget (~10-15 min per job,
py/kubeflow/tf_operator/tf_job_client.py:116-210). Here a 1-chief +
4-worker gang (the ResNet-50 BASELINE topology) must reach
AllReplicasReady in well under a second of controller work.

Prints ONE JSON line per backend:
    {"metric": ..., "value": N, "unit": "seconds", "vs_baseline": N}
vs_baseline = (reference implicit SLO lower bound, 600 s) / measured.

Backends (round-5 verdict #5 — both north stars measured per round):

- ``local``: subprocess data plane, the hermetic control loop.
- ``kube``: the SAME controller against the fake K8s apiserver with
  injected per-request latency (default 20 ms — a loaded production
  apiserver) and a fake kubelet that reports Running the moment it
  observes a pod. This prices the real deployment shape: reflector
  mirror, pod create round-trips, status patches, watch propagation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO_ROOT)

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.sdk import TPUJobClient

REFERENCE_SLO_SECONDS = 600.0  # lower bound of the reference e2e wait budget


def make_job(name: str, stub_dir: str, workers: int, chief: int) -> TPUJob:
    def spec(n: int) -> ReplicaSpec:
        return ReplicaSpec(
            replicas=n,
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(
                name=constants.DEFAULT_CONTAINER_NAME,
                command=[sys.executable, "-m",
                         "tf_operator_tpu.runtime.worker_stub"],
                env={"TPUJOB_STUB_DIR": stub_dir},
            )])))

    replica_specs = {"worker": spec(workers)}
    if chief:
        replica_specs["chief"] = spec(chief)
    return TPUJob(metadata=ObjectMeta(name=name),
                  spec=TPUJobSpec(replica_specs=replica_specs))


def measure_once(trial: int, workers: int, chief: int) -> float:
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        with tempfile.TemporaryDirectory() as stub_dir:
            job = make_job(f"bench-ready-{trial}", stub_dir, workers, chief)
            t0 = time.monotonic()
            client.create(job)
            deadline = t0 + 120.0
            while time.monotonic() < deadline:
                got = client.get(job.metadata.name)
                if got and got.status.all_replicas_ready_time is not None:
                    dt = time.monotonic() - t0
                    client.delete(job.metadata.name)
                    return dt
                time.sleep(0.01)
        raise TimeoutError("AllReplicasReady never latched")
    finally:
        op.stop()


def measure_once_kube(trial: int, workers: int, chief: int,
                      api_latency: float) -> float:
    """create -> AllReplicasReady against the fake apiserver with
    injected request latency and an immediate fake kubelet."""
    from tf_operator_tpu.runtime import store as store_mod
    from tf_operator_tpu.runtime.kube import (
        KubeClient,
        KubeConfig,
        KubeOperator,
        tpujob_to_k8s,
    )
    from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer

    fake = FakeKubeApiServer().start()
    fake.state.latency_seconds = api_latency
    op = KubeOperator(KubeClient(KubeConfig(server=fake.url)))
    stop = threading.Event()

    def kubelet() -> None:
        # The fake kubelet: report Running as soon as a pod appears
        # (zero container-start cost — the metric prices the CONTROL
        # PLANE, not image pulls).
        seen = set()
        q = fake.state.subscribe("pods")
        while not stop.is_set():
            try:
                etype, obj = q.get(timeout=0.2)
            except Exception:
                continue
            name = obj["metadata"]["name"]
            if etype == "ADDED" and name not in seen:
                seen.add(name)
                try:
                    fake.state.set_pod_phase("default", name, "Running")
                except Exception:
                    pass

    kubelet_t = threading.Thread(target=kubelet, daemon=True)
    kubelet_t.start()
    op.start(threadiness=2)
    try:
        job = make_job(f"bench-ready-kube-{trial}", "/tmp", workers, chief)
        body = tpujob_to_k8s(job)
        client = op.client
        t0 = time.monotonic()
        client.create(store_mod.TPUJOBS, "default", body)
        deadline = t0 + 120.0
        while time.monotonic() < deadline:
            raw = client.get(store_mod.TPUJOBS, "default",
                             job.metadata.name)
            if (raw.get("status") or {}).get("allReplicasReadyTime"):
                return time.monotonic() - t0
            time.sleep(0.01)
        raise TimeoutError("AllReplicasReady never latched (kube)")
    finally:
        stop.set()
        op.stop()
        fake.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="both",
                    choices=("local", "kube", "both"))
    ap.add_argument("--api-latency", type=float, default=0.02,
                    help="injected per-request apiserver latency for "
                         "--backend kube (seconds)")
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()
    workers, chief = 4, 1
    rc = 0
    if args.backend in ("local", "both"):
        try:
            best = min(measure_once(i, workers, chief)
                       for i in range(args.trials))
            print(json.dumps({
                "metric": (f"pod_to_all_replicas_ready_seconds"
                           f"[{chief}c+{workers}w]"),
                "value": round(best, 3),
                "unit": "seconds",
                "vs_baseline": round(REFERENCE_SLO_SECONDS / best, 1),
            }))
        except Exception as e:
            print(json.dumps({
                "metric": "pod_to_all_replicas_ready_seconds",
                "value": 0.0, "unit": "seconds", "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}"}))
            rc = 1
    if args.backend in ("kube", "both"):
        try:
            best = min(measure_once_kube(i, workers, chief,
                                         args.api_latency)
                       for i in range(args.trials))
            print(json.dumps({
                "metric": (f"pod_to_all_replicas_ready_seconds"
                           f"[kube,{chief}c+{workers}w,"
                           f"{int(args.api_latency * 1000)}ms_api]"),
                "value": round(best, 3),
                "unit": "seconds",
                "vs_baseline": round(REFERENCE_SLO_SECONDS / best, 1),
            }))
        except Exception as e:
            print(json.dumps({
                "metric": "pod_to_all_replicas_ready_seconds[kube]",
                "value": 0.0, "unit": "seconds", "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}"}))
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
