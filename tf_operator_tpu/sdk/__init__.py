"""Python SDK (reference: sdk/python/kubeflow/tfjob TFJobClient)."""

from tf_operator_tpu.sdk.client import TPUJobClient  # noqa: F401
