"""TPUJobClient: the user-facing job API.

Reference parity: sdk/python/kubeflow/tfjob/api/tf_job_client.py:55-446 —
create/get/patch/delete, wait_for_job/wait_for_condition, status
helpers (is_job_running/succeeded), get_pod_names/get_logs. The client
talks to a Store (in-process or served); conditions/statuses have the
same shape as the reference SDK's V1JobStatus.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Union

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import Pod, TPUJob
from tf_operator_tpu.controller import conditions as cond
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.store import EVENTS, Store


class TimeoutError_(TimeoutError):
    pass


class TPUJobClient:
    def __init__(self, store: Store, namespace: str = "default"):
        self.store = store
        self.namespace = namespace

    @classmethod
    def connect(cls, server_url: str,
                namespace: str = "default",
                token: Optional[str] = None,
                ca_file: Optional[str] = None,
                insecure_skip_verify: bool = False) -> "TPUJobClient":
        """Client against a served control plane (reference: TFJobClient
        building a kubernetes client from kubeconfig and talking HTTPS,
        tf_job_client.py:55-100). Works from any process or host:

            client = TPUJobClient.connect(
                "https://operator-host:8080",
                token="...", ca_file="/etc/tpu-operator/ca.pem")

        ``token`` is the bearer credential the server's token file
        grants (admin or read-only); ``ca_file`` verifies a self-signed
        server certificate. Defaults to $TPU_OPERATOR_TOKEN when unset.
        """
        import os

        from tf_operator_tpu.runtime.remote import RemoteStore

        token = token or os.environ.get("TPU_OPERATOR_TOKEN") or None
        return cls(RemoteStore(server_url, token=token, ca_file=ca_file,
                               insecure_skip_verify=insecure_skip_verify),
                   namespace=namespace)

    @classmethod
    def connect_kube(cls, kubeconfig: Optional[str] = None,
                     namespace: Optional[str] = None) -> "TPUJobClient":
        """Client directly against a Kubernetes cluster running the
        operator with ``--backend=kube`` — the reference SDK's shape
        (kubernetes-client from kubeconfig, tf_job_client.py:55-100):

            client = TPUJobClient.connect_kube()          # ~/.kube/config
            client = TPUJobClient.connect_kube("/path/to/kubeconfig")
        """
        from tf_operator_tpu.runtime.kube import (
            KubeClient,
            KubeConfig,
            KubeSdkStore,
        )

        config = KubeConfig.resolve(kubeconfig)
        ns = namespace or config.namespace or "default"
        return cls(KubeSdkStore(KubeClient(config), namespace=ns),
                   namespace=ns)

    # -- CRUD (reference tf_job_client.py:77-222) -----------------------

    def create(self, job: Union[TPUJob, dict],
               namespace: Optional[str] = None) -> TPUJob:
        if isinstance(job, dict):
            job = TPUJob.from_dict(job)
        if namespace:
            job.metadata.namespace = namespace
        elif not job.metadata.namespace:
            job.metadata.namespace = self.namespace
        return self.store.create(store_mod.TPUJOBS, job)

    def get(self, name: str, namespace: Optional[str] = None) -> TPUJob:
        return self.store.get(store_mod.TPUJOBS,
                              namespace or self.namespace, name)

    def patch(self, name: str, patch_fn: Callable[[TPUJob], None],
              namespace: Optional[str] = None) -> TPUJob:
        """Optimistic-concurrency read-modify-write (the SDK's patch)."""
        ns = namespace or self.namespace
        for _ in range(10):
            job = self.store.get(store_mod.TPUJOBS, ns, name)
            patch_fn(job)
            try:
                return self.store.update(store_mod.TPUJOBS, job)
            except store_mod.ConflictError:
                continue
        raise store_mod.ConflictError(f"patch of {ns}/{name} kept conflicting")

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self.store.delete(store_mod.TPUJOBS, namespace or self.namespace, name)

    def list(self, namespace: Optional[str] = None) -> List[TPUJob]:
        return self.store.list(store_mod.TPUJOBS,
                               namespace=namespace or self.namespace)

    # -- waiting (reference tf_job_client.py:223-305) -------------------

    def wait_for_condition(self, name: str, expected_condition: str,
                           timeout: float = 60.0,
                           namespace: Optional[str] = None,
                           poll_interval: float = 0.05) -> TPUJob:
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            last = self.get(name, namespace)
            if cond.has_condition(last.status, expected_condition):
                return last
            time.sleep(poll_interval)
        conds = [(c.type, c.status) for c in last.status.conditions] if last else []
        raise TimeoutError_(
            f"timed out waiting for {expected_condition} on {name}; "
            f"conditions={conds}")

    def wait_for_job(self, name: str, timeout: float = 60.0,
                     namespace: Optional[str] = None) -> TPUJob:
        """Wait until Succeeded or Failed (reference wait_for_job)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get(name, namespace)
            if cond.is_finished(job.status):
                return job
            time.sleep(0.05)
        raise TimeoutError_(f"timed out waiting for {name} to finish")

    def watch(self, name: Optional[str] = None,
              namespace: Optional[str] = None,
              timeout: Optional[float] = None,
              until_finished: bool = False):
        """Generator of ``(event_type, TPUJob)`` — the reference
        TFJobWatch analog (sdk api/tf_job_watch.py). Existing jobs are
        replayed as ADDED, then live events stream until ``timeout``
        elapses, the generator is closed, or (with ``until_finished``)
        the named job reaches a terminal condition."""
        import queue as _queue

        ns = namespace or self.namespace
        q: "_queue.Queue" = _queue.Queue()
        watcher = self.store.watch(
            store_mod.TPUJOBS, lambda et, obj: q.put((et, obj)))
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                try:
                    event_type, job = q.get(timeout=remaining)
                except _queue.Empty:
                    return
                if job.metadata.namespace != ns:
                    continue
                if name is not None and job.metadata.name != name:
                    continue
                yield event_type, job
                if (until_finished and name is not None
                        and (cond.is_finished(job.status)
                             or event_type == store_mod.DELETED)):
                    # DELETED is terminal too: no further events for
                    # this job will ever arrive.
                    return
        finally:
            watcher.stop()

    def wait_for_delete(self, name: str, timeout: float = 60.0,
                        namespace: Optional[str] = None) -> None:
        deadline = time.monotonic() + timeout
        ns = namespace or self.namespace
        while time.monotonic() < deadline:
            if self.store.try_get(store_mod.TPUJOBS, ns, name) is None:
                return
            time.sleep(0.05)
        raise TimeoutError_(f"timed out waiting for {name} to be deleted")

    # -- status helpers (reference tf_job_client.py:306-342) ------------

    def get_job_status(self, name: str,
                       namespace: Optional[str] = None) -> str:
        job = self.get(name, namespace)
        if job.status.conditions:
            return job.status.conditions[-1].type
        return ""

    def is_job_running(self, name: str, namespace: Optional[str] = None) -> bool:
        return cond.is_running(self.get(name, namespace).status)

    def is_job_succeeded(self, name: str,
                         namespace: Optional[str] = None) -> bool:
        return cond.is_succeeded(self.get(name, namespace).status)

    # -- pods (reference tf_job_client.py:343-446) ----------------------

    def get_pod_names(self, name: str, namespace: Optional[str] = None,
                      replica_type: Optional[str] = None,
                      replica_index: Optional[int] = None) -> List[str]:
        selector: Dict[str, str] = {
            constants.LABEL_GROUP_NAME: constants.GROUP,
            constants.LABEL_JOB_NAME: name,
        }
        if replica_type is not None:
            selector[constants.LABEL_REPLICA_TYPE] = replica_type.lower()
        if replica_index is not None:
            selector[constants.LABEL_REPLICA_INDEX] = str(replica_index)
        pods = self.store.list(store_mod.PODS,
                               namespace=namespace or self.namespace,
                               selector=selector)
        return sorted(p.metadata.name for p in pods)

    def get_pods(self, name: str, namespace: Optional[str] = None) -> List[Pod]:
        return self.store.list(
            store_mod.PODS, namespace=namespace or self.namespace,
            selector={constants.LABEL_GROUP_NAME: constants.GROUP,
                      constants.LABEL_JOB_NAME: name})

    def get_logs(self, pod_name: str, namespace: Optional[str] = None,
                 tail_lines: Optional[int] = None) -> str:
        """One pod's captured stdout/stderr (reference
        tf_job_client.py:380-446 read_namespaced_pod_log analog). Against
        a served control plane this reads through the API server's log
        proxy (kubelet log API); in-process it reads the local file."""
        ns = namespace or self.namespace
        read_remote = getattr(self.store, "read_logs", None)
        if read_remote is not None:
            return read_remote(ns, pod_name, tail_lines=tail_lines)
        pod = self.store.try_get(store_mod.PODS, ns, pod_name)
        if pod is None or not pod.status.log_path:
            return ""
        try:
            with open(pod.status.log_path, errors="replace") as f:
                text = f.read()
        except OSError:
            return ""
        if tail_lines is not None:
            lines = text.splitlines()[-tail_lines:] if tail_lines > 0 else []
            text = "\n".join(lines)
        return text

    def stream_logs(self, pod_name: str, namespace: Optional[str] = None):
        """Follow one pod's log live until it reaches a terminal phase
        (kubectl logs -f). Yields text chunks."""
        import os as _os

        ns = namespace or self.namespace
        remote = getattr(self.store, "stream_logs", None)
        if remote is not None:
            yield from remote(ns, pod_name)
            return
        pos = 0
        while True:
            pod = self.store.try_get(store_mod.PODS, ns, pod_name)
            path = pod.status.log_path if pod is not None else ""
            chunk = b""
            if path and _os.path.exists(path):
                # Binary reads with byte offsets: a text-mode seek with a
                # character count lands mid-codepoint on non-ASCII logs.
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read()
            if chunk:
                pos += len(chunk)
                yield chunk.decode(errors="replace")
                continue
            from tf_operator_tpu.api.types import PodPhase

            if pod is None or pod.status.phase in (PodPhase.SUCCEEDED,
                                                   PodPhase.FAILED):
                return
            time.sleep(0.05)

    def follow_job_logs(self, name: str, namespace: Optional[str] = None,
                        replica_type: Optional[str] = None,
                        timeout: Optional[float] = None):
        """Interleaved live tail across every pod of a job (the reference
        SDK's multi-pod follow, tf_job_client.py:380-446: one thread +
        queue per pod). Yields ``(pod_name, chunk)`` until every pod's
        stream ends or ``timeout`` elapses."""
        import queue as _queue
        import threading as _threading

        pods = self.get_pod_names(name, namespace=namespace,
                                  replica_type=replica_type)
        # Bounded queue + stop flag: when the consumer stops (timeout or
        # generator close), pumps must not keep accumulating chunks
        # forever for a still-running job.
        q: "_queue.Queue" = _queue.Queue(maxsize=256)
        stop = _threading.Event()
        done = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    continue
            return False

        def pump(pod_name: str) -> None:
            try:
                for chunk in self.stream_logs(pod_name, namespace=namespace):
                    if not put((pod_name, chunk)):
                        return
            finally:
                put((pod_name, done))

        threads = [_threading.Thread(target=pump, args=(p,), daemon=True)
                   for p in pods]
        for t in threads:
            t.start()
        live = set(pods)
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while live:
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                try:
                    pod_name, chunk = q.get(timeout=remaining)
                except _queue.Empty:
                    return
                if chunk is done:
                    live.discard(pod_name)
                    continue
                yield pod_name, chunk
        finally:
            stop.set()

    def get_job_logs(self, name: str, namespace: Optional[str] = None,
                     replica_type: Optional[str] = None,
                     tail_lines: Optional[int] = None) -> Dict[str, str]:
        """Logs for every pod of a job, keyed by pod name (the
        reference's multi-pod get_logs surface)."""
        return {
            pod_name: self.get_logs(pod_name, namespace=namespace,
                                    tail_lines=tail_lines)
            for pod_name in self.get_pod_names(
                name, namespace=namespace, replica_type=replica_type)
        }

    def get_events(self, name: str, namespace: Optional[str] = None,
                   reason: str = "") -> List:
        """Lifecycle events for a job and its pods (K8s Events analog,
        persisted by the operator's recorder, attributed by the job-name
        label — never by name-prefix matching)."""
        ns = namespace or self.namespace
        selector = {constants.LABEL_JOB_NAME: name}
        return [e for e in self.store.list(EVENTS, namespace=ns,
                                           selector=selector)
                if not reason or e.reason == reason]

    def get_creation_failures(self, name: str,
                              namespace: Optional[str] = None) -> List[str]:
        """Messages of FailedCreate-class events for a job (reference
        get_creation_failures_from_tfjob, tf_job_client.py:363)."""
        return [e.message for e in self.get_events(name, namespace=namespace)
                if e.reason.startswith("FailedCreate")]

    # -- explain (flight-recorder decision journal) ---------------------

    def explain(self, name: str,
                namespace: Optional[str] = None) -> Dict:
        """Why is my job in this state — answered from the operator,
        not from log archaeology (docs/observability.md): the job's
        conditions, its decision-journal records (every admission
        defer/deny, barrier open/resolve, displacement, and resize the
        control plane decided, with reasons and trace ids), and its
        recent lifecycle events.

        The journal is read in-process (runtime/trace.py JOURNAL) —
        against a remote store this surface carries conditions/events
        only; the journal of a remote operator is served by ITS
        monitoring endpoint at ``/debug/jobs/<ns>/<name>``."""
        ns = namespace or self.namespace
        job = self.get(name, ns)
        from tf_operator_tpu.runtime import trace as trace_lib

        decisions = trace_lib.JOURNAL.decisions(ns, name) or []
        return {
            "namespace": ns,
            "name": name,
            "phase": (job.status.conditions[-1].type
                      if job.status.conditions else ""),
            "conditions": [
                {"type": c.type, "status": c.status, "reason": c.reason,
                 "message": c.message}
                for c in job.status.conditions],
            "decisions": decisions,
            "events": [
                {"type": e.type, "reason": e.reason, "message": e.message}
                for e in self.get_events(name, namespace=ns)[-20:]],
        }

    def explain_text(self, name: str,
                     namespace: Optional[str] = None) -> str:
        """``tpujob explain``-style rendering of :meth:`explain` (the
        CLI surface: ``python -c`` one-liners and notebooks print it)."""
        info = self.explain(name, namespace=namespace)
        lines = [f"TPUJob {info['namespace']}/{info['name']}: "
                 f"{info['phase'] or 'no conditions yet'}"]
        for c in info["conditions"]:
            lines.append(f"  condition {c['type']}={c['status']} "
                         f"({c['reason']}): {c['message']}")
        if info["decisions"]:
            lines.append("  decision journal (oldest first):")
            for d in info["decisions"]:
                count = f" x{d['count']}" if d.get("count", 1) > 1 else ""
                tid = f" [{d['trace_id']}]" if d.get("trace_id") else ""
                lines.append(f"    {d['kind']}/{d['reason']}{count}"
                             f"{tid}: {d['message']}")
        else:
            lines.append("  decision journal: no control-plane decision "
                         "has touched this job")
        return "\n".join(lines)
