"""ctypes bindings for the native prefetching batch loader (libloader.so).

Producer threads in C++ synthesize batches into a ring ahead of the
consumer, overlapping input generation with the training step entirely
outside the GIL. Returns None from ``create_*`` when the toolchain or
library is unavailable — callers (train/data.py) fall back to the
Python generators.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from tf_operator_tpu.native import load_library

KIND_IMAGES = 0
KIND_TOKENS = 1


def _load() -> Optional[ctypes.CDLL]:
    lib = load_library("libloader.so")
    if lib is None or hasattr(lib, "_tpuop_configured"):
        return lib
    lib._tpuop_configured = True
    lib.tpuop_loader_create.restype = ctypes.c_void_p
    lib.tpuop_loader_create.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64]
    lib.tpuop_loader_next.restype = ctypes.c_int64
    lib.tpuop_loader_next.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32)]
    lib.tpuop_loader_produced.restype = ctypes.c_int64
    lib.tpuop_loader_produced.argtypes = [ctypes.c_void_p]
    lib.tpuop_loader_destroy.argtypes = [ctypes.c_void_p]
    return lib


def available() -> bool:
    return _load() is not None


class NativeLoader:
    """Iterator of prefetched batches; call ``close()`` (or use as a
    context manager) to stop the producer threads."""

    def __init__(self, kind: int, dims, cardinality: int,
                 depth: int = 4, threads: int = 2, seed: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader library unavailable")
        self._lib = lib
        self.kind = kind
        self.dims = tuple(int(d) for d in dims)
        c_dims = (ctypes.c_int64 * 4)(*(list(self.dims) + [0] * 4)[:4])
        self._handle = lib.tpuop_loader_create(
            kind, c_dims, cardinality, depth, threads,
            ctypes.c_uint64(seed))
        self._closed = False
        # Serializes next/close so the handle is never used after free
        # (close from another thread waits out an in-flight next, which
        # is bounded: producers run until destroy).
        self._call_lock = threading.Lock()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # Dropped without close(): stop the producer threads rather than
        # leaking them (and the ring buffers) for the process lifetime.
        try:
            self.close()
        except Exception:
            pass

    def close(self) -> None:
        if getattr(self, "_closed", True):
            return
        with self._call_lock:
            if not self._closed and self._handle:
                self._lib.tpuop_loader_destroy(self._handle)
                self._closed = True

    def produced(self) -> int:
        return int(self._lib.tpuop_loader_produced(self._handle))

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        with self._call_lock:
            if self._closed:
                raise StopIteration
            if self.kind == KIND_IMAGES:
                b, h, w, c = self.dims
                main = np.empty((b, h, w, c), np.float32)
                aux = np.empty((b,), np.int32)
                idx = self._lib.tpuop_loader_next(
                    self._handle, main.ctypes.data_as(ctypes.c_void_p),
                    aux.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
                if idx < 0:
                    raise StopIteration
                return {"inputs": main, "labels": aux}
            b, s = self.dims[:2]
            main = np.empty((b, s), np.int32)
            idx = self._lib.tpuop_loader_next(
                self._handle, main.ctypes.data_as(ctypes.c_void_p), None)
            if idx < 0:
                raise StopIteration
            return {"inputs": main}


def create_images(batch_size: int, image_size: int = 224,
                  num_classes: int = 1000, depth: int = 4,
                  threads: int = 2, seed: int = 0) -> Optional[NativeLoader]:
    if not available():
        return None
    return NativeLoader(KIND_IMAGES,
                        (batch_size, image_size, image_size, 3),
                        num_classes, depth=depth, threads=threads, seed=seed)


def create_tokens(batch_size: int, seq_len: int, vocab_size: int,
                  depth: int = 4, threads: int = 2,
                  seed: int = 0) -> Optional[NativeLoader]:
    if not available():
        return None
    return NativeLoader(KIND_TOKENS, (batch_size, seq_len, 0, 0),
                        vocab_size, depth=depth, threads=threads, seed=seed)
