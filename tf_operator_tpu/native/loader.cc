// Threaded prefetching batch loader.
//
// The data-loader runtime layer: producer threads synthesize batches
// ahead of consumption into a ring of slots, so batch generation
// overlaps the training step instead of serializing with it (and never
// touches the Python GIL). Batch contents are deterministic in
// (seed, batch_index) regardless of thread count or interleaving.
//
// C ABI for ctypes; see tf_operator_tpu/native/__init__.py.
//
// Build: make -C tf_operator_tpu/native   (produces libloader.so)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t& state) {
  state += 0x9E3779B97F4A7C15ULL;
  uint64_t z = state;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

enum Kind : int32_t {
  kImages = 0,  // main: f32 [b,h,w,c] in [0,1); aux: i32 labels [b]
  kTokens = 1,  // main: i32 [b,s]; aux: unused
};

struct Slot {
  std::vector<uint8_t> main;
  std::vector<int32_t> aux;
  int64_t batch_index = -1;  // which batch currently occupies the slot
  bool ready = false;
};

struct Loader {
  int32_t kind;
  int64_t batch, d1, d2, d3;  // images: b,h,w,c; tokens: b,s,-,-
  int32_t cardinality;        // classes (images) or vocab (tokens)
  uint64_t seed;
  size_t main_bytes;
  size_t aux_count;

  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits
  std::condition_variable cv_free;    // producers wait
  std::condition_variable cv_idle;    // destroy waits for consumers
  std::atomic<int64_t> next_to_produce{0};
  int64_t next_to_consume = 0;        // guarded by mu
  std::atomic<int64_t> produced{0};
  bool stopping = false;              // guarded by mu
  int active_next = 0;                // consumers inside _next (mu)

  std::vector<std::thread> workers;

  void fill(Slot& slot, int64_t batch_index) {
    uint64_t state = seed ^ (0xD1B54A32D192ED03ULL * (batch_index + 1));
    if (kind == kImages) {
      float* out = reinterpret_cast<float*>(slot.main.data());
      size_t n = main_bytes / sizeof(float);
      for (size_t i = 0; i < n; ++i) {
        out[i] = static_cast<float>(splitmix64(state) >> 40) * 0x1.0p-24f;
      }
      for (size_t i = 0; i < aux_count; ++i) {
        slot.aux[i] = static_cast<int32_t>(
            splitmix64(state) % static_cast<uint64_t>(cardinality));
      }
    } else {
      int32_t* out = reinterpret_cast<int32_t*>(slot.main.data());
      size_t n = main_bytes / sizeof(int32_t);
      for (size_t i = 0; i < n; ++i) {
        out[i] = static_cast<int32_t>(
            splitmix64(state) % static_cast<uint64_t>(cardinality));
      }
    }
    slot.batch_index = batch_index;
  }

  void worker() {
    for (;;) {
      int64_t idx = next_to_produce.fetch_add(1);
      Slot& slot = slots[idx % slots.size()];
      {
        // Wait until the slot's previous occupant has been consumed.
        std::unique_lock<std::mutex> lock(mu);
        cv_free.wait(lock, [&] {
          return stopping || (!slot.ready && next_to_consume + static_cast<int64_t>(slots.size()) > idx);
        });
        if (stopping) return;
      }
      fill(slot, idx);
      {
        std::lock_guard<std::mutex> lock(mu);
        slot.ready = true;
        produced.fetch_add(1);
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// dims: images -> {b, h, w, c}; tokens -> {b, s, 0, 0}.
void* tpuop_loader_create(int32_t kind, const int64_t* dims,
                          int32_t cardinality, int32_t depth,
                          int32_t threads, uint64_t seed) {
  auto* ld = new Loader();
  ld->kind = kind;
  ld->batch = dims[0];
  ld->d1 = dims[1];
  ld->d2 = dims[2];
  ld->d3 = dims[3];
  ld->cardinality = cardinality > 0 ? cardinality : 1;
  ld->seed = seed;
  if (kind == kImages) {
    ld->main_bytes = static_cast<size_t>(dims[0]) * dims[1] * dims[2] *
                     dims[3] * sizeof(float);
    ld->aux_count = static_cast<size_t>(dims[0]);
  } else {
    ld->main_bytes = static_cast<size_t>(dims[0]) * dims[1] * sizeof(int32_t);
    ld->aux_count = 0;
  }
  if (depth < 2) depth = 2;
  ld->slots.resize(depth);
  for (auto& s : ld->slots) {
    s.main.resize(ld->main_bytes);
    s.aux.resize(ld->aux_count);
  }
  if (threads < 1) threads = 1;
  if (threads > 16) threads = 16;
  for (int t = 0; t < threads; ++t) {
    ld->workers.emplace_back([ld] { ld->worker(); });
  }
  return ld;
}

// Copies the next sequential batch into out_main (and out_aux when the
// kind has labels). Returns the batch index, or -1 if stopped.
int64_t tpuop_loader_next(void* handle, void* out_main, int32_t* out_aux) {
  auto* ld = static_cast<Loader*>(handle);
  int64_t want;
  Slot* slot;
  {
    std::unique_lock<std::mutex> lock(ld->mu);
    if (ld->stopping) return -1;
    ++ld->active_next;  // destroy() drains active consumers before freeing
    want = ld->next_to_consume;
    slot = &ld->slots[want % ld->slots.size()];
    ld->cv_ready.wait(lock, [&] {
      return ld->stopping || (slot->ready && slot->batch_index == want);
    });
    if (ld->stopping) {
      --ld->active_next;
      ld->cv_idle.notify_all();
      return -1;
    }
  }
  std::memcpy(out_main, slot->main.data(), ld->main_bytes);
  if (out_aux && ld->aux_count) {
    std::memcpy(out_aux, slot->aux.data(),
                ld->aux_count * sizeof(int32_t));
  }
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    slot->ready = false;
    ld->next_to_consume = want + 1;
    --ld->active_next;
  }
  ld->cv_free.notify_all();
  ld->cv_idle.notify_all();
  return want;
}

int64_t tpuop_loader_produced(void* handle) {
  return static_cast<Loader*>(handle)->produced.load();
}

void tpuop_loader_destroy(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->stopping = true;
  }
  ld->cv_free.notify_all();
  ld->cv_ready.notify_all();
  {
    // A consumer may be blocked inside tpuop_loader_next (e.g. a
    // feeder thread); wait until it has left before freeing.
    std::unique_lock<std::mutex> lock(ld->mu);
    ld->cv_idle.wait(lock, [&] { return ld->active_next == 0; });
  }
  for (auto& t : ld->workers) t.join();
  delete ld;
}

}  // extern "C"
