// Native batch generation / image preprocessing.
//
// The input pipeline is host-side and competes with the Python process for
// cycles; on TPU VMs the HBM-feeding path must not be GIL-bound. This
// library provides the hot loops — synthetic batch fills (benchmarking)
// and uint8->float32 image normalization (the real decode-side hot path) —
// multithreaded in C++, exposed through a plain C ABI for ctypes.
//
// Build: make -C tf_operator_tpu/native   (produces libbatchgen.so)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxThreads = 16;

inline uint64_t splitmix64(uint64_t& state) {
  state += 0x9E3779B97F4A7C15ULL;
  uint64_t z = state;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

template <typename Fn>
void parallel_chunks(int64_t n, Fn fn) {
  int threads = std::min<int64_t>(
      kMaxThreads, std::max<int64_t>(1, n / (1 << 16)));
  if (threads <= 1) {
    fn(0, 0, n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = std::min<int64_t>(n, begin + chunk);
    if (begin >= end) break;
    pool.emplace_back([=] { fn(t, begin, end); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Uniform [0, 1) float fill.
void tpuop_fill_uniform_f32(float* out, int64_t n, uint64_t seed) {
  parallel_chunks(n, [&](int t, int64_t begin, int64_t end) {
    uint64_t state = seed + 0x632BE59BD9B4E019ULL * (t + 1);
    for (int64_t i = begin; i < end; ++i) {
      out[i] = static_cast<float>(splitmix64(state) >> 40) * 0x1.0p-24f;
    }
  });
}

// Uniform integer fill in [low, high).
void tpuop_fill_randint_i32(int32_t* out, int64_t n, int32_t low,
                            int32_t high, uint64_t seed) {
  uint64_t range = static_cast<uint64_t>(high - low);
  if (range == 0) {
    std::memset(out, 0, n * sizeof(int32_t));
    return;
  }
  parallel_chunks(n, [&](int t, int64_t begin, int64_t end) {
    uint64_t state = seed + 0x9E3779B97F4A7C15ULL * (t + 1);
    for (int64_t i = begin; i < end; ++i) {
      out[i] = low + static_cast<int32_t>(splitmix64(state) % range);
    }
  });
}

// uint8 HWC image -> float32, per-channel (x/255 - mean) / std.
void tpuop_normalize_u8_f32(const uint8_t* in, float* out, int64_t n_pixels,
                            const float* mean, const float* std_dev,
                            int32_t channels) {
  std::vector<float> scale(channels), shift(channels);
  for (int c = 0; c < channels; ++c) {
    scale[c] = 1.0f / (255.0f * std_dev[c]);
    shift[c] = -mean[c] / std_dev[c];
  }
  int64_t n = n_pixels * channels;
  parallel_chunks(n, [&](int, int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      int c = static_cast<int>(i % channels);
      out[i] = static_cast<float>(in[i]) * scale[c] + shift[c];
    }
  });
}

}  // extern "C"
