"""ctypes bindings for the native batch generator (libbatchgen.so).

Builds lazily via make on first use; all entry points degrade to numpy
when the toolchain or library is unavailable, so the Python path never
hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("tpu_operator.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_loaded: dict = {}  # lib filename -> CDLL | None (None = tried, failed)


def load_library(lib_name: str) -> Optional[ctypes.CDLL]:
    """Build (make -C, once) and dlopen a native library from this
    directory; None when the toolchain or library is unavailable.
    Shared by every native binding module."""
    with _lock:
        if lib_name in _loaded:
            return _loaded[lib_name]
        _loaded[lib_name] = None  # one attempt per process
        path = os.path.join(_DIR, lib_name)
        if not os.path.exists(path):
            try:
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception as e:
                log.info("native build unavailable (%s); using fallback", e)
                return None
        try:
            _loaded[lib_name] = ctypes.CDLL(path)
        except OSError as e:
            log.info("failed to load %s (%s); using fallback", path, e)
        return _loaded[lib_name]


def _load() -> Optional[ctypes.CDLL]:
    lib = load_library("libbatchgen.so")
    if lib is None or hasattr(lib, "_tpuop_configured"):
        return lib
    lib._tpuop_configured = True
    lib.tpuop_fill_uniform_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_uint64]
    lib.tpuop_fill_randint_i32.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64]
    lib.tpuop_normalize_u8_f32.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int32]
    return lib


def available() -> bool:
    return _load() is not None


def fill_uniform(shape, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(shape, np.float32)
    if lib is None:
        rng = np.random.default_rng(seed)
        out[...] = rng.random(shape, dtype=np.float32)
        return out
    lib.tpuop_fill_uniform_f32(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size, ctypes.c_uint64(seed))
    return out


def fill_randint(shape, low: int, high: int, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(shape, np.int32)
    if lib is None:
        rng = np.random.default_rng(seed)
        out[...] = rng.integers(low, high, shape, dtype=np.int32)
        return out
    lib.tpuop_fill_randint_i32(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.size, low, high, ctypes.c_uint64(seed))
    return out


def normalize_images(images_u8: np.ndarray, mean, std) -> np.ndarray:
    """[..., C] uint8 -> float32 (x/255 - mean)/std per channel."""
    images_u8 = np.ascontiguousarray(images_u8, dtype=np.uint8)
    channels = images_u8.shape[-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    lib = _load()
    if lib is None:
        return (images_u8.astype(np.float32) / 255.0 - mean) / std
    out = np.empty(images_u8.shape, np.float32)
    lib.tpuop_normalize_u8_f32(
        images_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        images_u8.size // channels,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        channels)
    return out
