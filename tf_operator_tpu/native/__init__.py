"""ctypes bindings for the native batch generator (libbatchgen.so).

Builds lazily via make on first use; all entry points degrade to numpy
when the toolchain or library is unavailable, so the Python path never
hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("tpu_operator.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbatchgen.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception as e:
                log.info("native batchgen unavailable (%s); using numpy", e)
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.info("failed to load %s (%s); using numpy", _LIB_PATH, e)
            return None
        lib.tpuop_fill_uniform_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_uint64]
        lib.tpuop_fill_randint_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64]
        lib.tpuop_normalize_u8_f32.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int32]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def fill_uniform(shape, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(shape, np.float32)
    if lib is None:
        rng = np.random.default_rng(seed)
        out[...] = rng.random(shape, dtype=np.float32)
        return out
    lib.tpuop_fill_uniform_f32(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size, ctypes.c_uint64(seed))
    return out


def fill_randint(shape, low: int, high: int, seed: int) -> np.ndarray:
    lib = _load()
    out = np.empty(shape, np.int32)
    if lib is None:
        rng = np.random.default_rng(seed)
        out[...] = rng.integers(low, high, shape, dtype=np.int32)
        return out
    lib.tpuop_fill_randint_i32(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.size, low, high, ctypes.c_uint64(seed))
    return out


def normalize_images(images_u8: np.ndarray, mean, std) -> np.ndarray:
    """[..., C] uint8 -> float32 (x/255 - mean)/std per channel."""
    images_u8 = np.ascontiguousarray(images_u8, dtype=np.uint8)
    channels = images_u8.shape[-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    lib = _load()
    if lib is None:
        return (images_u8.astype(np.float32) / 255.0 - mean) / std
    out = np.empty(images_u8.shape, np.float32)
    lib.tpuop_normalize_u8_f32(
        images_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        images_u8.size // channels,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        channels)
    return out
