"""Mixtral-family MoE decoder (BASELINE config: "Mixtral 8x7B
expert-parallel multi-slice v5p, DCN all-to-all").

TPU-first MoE: GShard-style dense einsum dispatch — router top-k picks
experts, tokens are packed into per-expert capacity buffers with one-hot
dispatch/combine tensors, expert FFNs run as batched einsums over a
leading expert dim. Expert params shard over the ``ep`` mesh axis
(MOE_RULES), so XLA lowers the dispatch/combine einsums to all-to-alls
(ICI within a slice, DCN across slices) — no hand-written comm.

Shares the attention stack with the Llama family.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.models.llama import (
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
)
from tf_operator_tpu.ops.layers import rope_frequencies


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.02
    max_seq_len: int = 8192
    rope_theta: float = 1000000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attention_impl: str = ""
    sp_axis: str = "sp"

    def attention_config(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden=self.hidden,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, head_dim=self.head_dim,
            mlp_dim=self.mlp_dim, max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta, dtype=self.dtype, remat=self.remat,
            attention_impl=self.attention_impl, sp_axis=self.sp_axis)


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def mixtral_tiny(vocab_size: int = 256, max_seq_len: int = 128) -> MixtralConfig:
    return MixtralConfig(vocab_size=vocab_size, hidden=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, head_dim=16, mlp_dim=128,
                         n_experts=4, experts_per_token=2,
                         max_seq_len=max_seq_len, rope_theta=10000.0,
                         remat=False)


class MoELayer(nn.Module):
    """Token-choice top-k routing with capacity; dense einsum dispatch."""

    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        b, s, h = x.shape
        t = b * s
        e = cfg.n_experts
        k = cfg.experts_per_token
        capacity = max(k, int(t * k * cfg.capacity_factor / e))

        xt = x.reshape(t, h)
        router_logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                                 param_dtype=jnp.float32,
                                 name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]

        # top-k expert choice per token
        top_probs, top_idx = jax.lax.top_k(probs, k)             # [T, K]
        top_probs = top_probs / jnp.maximum(
            jnp.sum(top_probs, axis=-1, keepdims=True), 1e-9)

        # capacity positions: for each (expert, k) assignment, this token's
        # slot is the count of earlier tokens choosing the same expert
        expert_onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.int32)  # [T,K,E]
        flat_assign = expert_onehot.reshape(t * k, e)
        position = (jnp.cumsum(flat_assign, axis=0) - flat_assign)    # [T*K,E]
        position = jnp.sum(position * flat_assign, axis=-1).reshape(t, k)
        within_capacity = position < capacity                    # [T, K]

        # dispatch [T, E, C] / combine [T, E, C]
        pos_onehot = jax.nn.one_hot(position, capacity,
                                    dtype=x.dtype)               # [T,K,C]
        disp = (expert_onehot.astype(x.dtype)[..., None]
                * pos_onehot[:, :, None, :]
                * within_capacity.astype(x.dtype)[:, :, None, None])
        dispatch = jnp.sum(disp, axis=1)                         # [T,E,C]
        combine = jnp.sum(disp * top_probs.astype(x.dtype)[:, :, None, None],
                          axis=1)                                # [T,E,C]

        # expert buffers + batched expert FFNs (leading dim e -> ep axis)
        expert_in = jnp.einsum("tec,th->ech", dispatch, xt,
                               preferred_element_type=jnp.float32
                               ).astype(cfg.dtype)               # [E,C,H]
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (e, h, cfg.mlp_dim), jnp.float32)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (e, h, cfg.mlp_dim), jnp.float32)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (e, cfg.mlp_dim, h), jnp.float32)
        gate = jnp.einsum("ech,ehm->ecm", expert_in, w_gate.astype(cfg.dtype))
        up = jnp.einsum("ech,ehm->ecm", expert_in, w_up.astype(cfg.dtype))
        act = nn.silu(gate) * up
        expert_out = jnp.einsum("ecm,emh->ech", act,
                                w_down.astype(cfg.dtype))        # [E,C,H]

        y = jnp.einsum("tec,ech->th", combine, expert_out)
        y = y.reshape(b, s, h).astype(x.dtype)

        # load-balancing aux loss (Switch/GShard): E * sum_e f_e * P_e
        assigned = jnp.sum(dispatch, axis=-1)                    # [T, E]
        f = jnp.mean(assigned.astype(jnp.float32), axis=0)       # frac routed
        p = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(f * p) / k
        return y, aux


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        x = x + LlamaAttention(cfg.attention_config(), name="attn")(
            RMSNorm(name="attn_norm")(x), angles)
        moe_out, aux = MoELayer(cfg, name="moe")(RMSNorm(name="mlp_norm")(x))
        return x + moe_out, aux


class Mixtral(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Returns (logits, aux_loss)."""
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed_tokens")(tokens)
        angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                  cfg.rope_theta)

        block = MixtralBlock
        if cfg.remat:
            block = nn.remat(block, prevent_cse=False)
        ScanBlocks = nn.scan(
            block,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, aux = ScanBlocks(cfg, name="blocks")(x, angles)

        x = RMSNorm(name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits, jnp.mean(aux)


_MOE_LEAF_AXES = {
    ("router", "kernel"): ("embed", None),
    ("w_gate",): ("expert", "embed", "mlp"),
    ("w_up",): ("expert", "embed", "mlp"),
    ("w_down",): ("expert", "mlp", "embed"),
}


def param_logical_axes(path: Tuple[str, ...], value):
    """Mixtral logical axes: MoE params + the shared Llama mapping."""
    from tf_operator_tpu.models.llama import param_logical_axes as base_axes

    path = tuple(path)
    for suffix, axes in _MOE_LEAF_AXES.items():
        if path[-len(suffix):] == suffix:
            ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
            if len(axes) == ndim:
                return axes
            if len(axes) + 1 == ndim and "blocks" in path:
                return ("layers",) + axes
            break
    else:
        return base_axes(path, value)
    raise ValueError(f"no logical axes for MoE param {'/'.join(path)}")


def make_moe_lm_loss(aux_loss_weight: float = 0.02):
    """LM loss + weighted load-balancing aux loss."""
    from tf_operator_tpu.train.trainer import cross_entropy_loss

    def moe_lm_loss(params, extra_vars, batch, model_apply):
        tokens = batch["inputs"]
        logits, aux = model_apply({"params": params}, tokens[:, :-1])
        ce = cross_entropy_loss(logits, tokens[:, 1:], batch.get("mask"))
        return ce + aux * aux_loss_weight, extra_vars

    moe_lm_loss.model_inputs_fn = lambda b: (b["inputs"][:, :-1],)
    return moe_lm_loss
