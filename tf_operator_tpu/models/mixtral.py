"""Mixtral-family MoE decoder (BASELINE config: "Mixtral 8x7B
expert-parallel multi-slice v5p, DCN all-to-all").

TPU-first MoE with two numerics-equivalent dispatch implementations
selected by ``MixtralConfig.dispatch``:

- ``"einsum"`` (default): GShard-style dense einsum dispatch — router
  top-k picks experts, tokens are packed into per-expert capacity
  buffers with one-hot dispatch/combine tensors contracted by dense
  einsums. Simple and GSPMD-friendly, but the one-hot contractions
  execute O(T·E·C·H) matmul FLOPs and move O(T·E·C) bytes for what is
  fundamentally a permutation — at the bench config that is ~5× the
  expert FFN FLOPs (docs/benchmarks.md MoE roofline).
- ``"gather"``: sort/gather token routing — a stable argsort of the
  (token, slot) assignments by expert, a row-gather into the identical
  capacity-packed [E, C, H] buffers, and a weighted inverse-permutation
  scatter to combine. Same capacity dropping (the stable sort preserves
  the einsum path's token-major priority order), same top-k probs, same
  aux loss; the routing tensors shrink from O(T·E·C) floats to O(T·K)
  integers and the permutation costs gather/scatter bandwidth instead
  of matmul FLOPs.

Both paths run the identical batched expert FFN einsums over a leading
expert dim. Expert params shard over the ``ep`` mesh axis (MOE_RULES),
so XLA lowers the pack/unpack — einsum contractions or gather/scatter —
to all-to-alls (ICI within a slice, DCN across slices); no hand-written
comm.

Shares the attention stack with the Llama family.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.models.llama import (
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
)
from tf_operator_tpu.ops.layers import rope_frequencies


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    # Routing implementation: "einsum" (one-hot dispatch/combine einsums,
    # the GShard formulation) or "gather" (argsort + gather/scatter token
    # permutation). Numerics-equivalent; see module docstring.
    dispatch: str = "einsum"
    aux_loss_weight: float = 0.02
    max_seq_len: int = 8192
    rope_theta: float = 1000000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attention_impl: str = ""
    sp_axis: str = "sp"
    # Incremental-decode mode (the serving plane): the shared attention
    # stack reads/writes its causal KV cache exactly as in the Llama
    # family (LlamaConfig.decode) — MoE routing is stateless per token,
    # so decode only changes the attention branch. Param tree unchanged;
    # trained checkpoints load into the decode model as-is.
    decode: bool = False

    def attention_config(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden=self.hidden,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, head_dim=self.head_dim,
            mlp_dim=self.mlp_dim, max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta, dtype=self.dtype, remat=self.remat,
            attention_impl=self.attention_impl, sp_axis=self.sp_axis,
            decode=self.decode)


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def mixtral_tiny(vocab_size: int = 256, max_seq_len: int = 128) -> MixtralConfig:
    return MixtralConfig(vocab_size=vocab_size, hidden=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, head_dim=16, mlp_dim=128,
                         n_experts=4, experts_per_token=2,
                         max_seq_len=max_seq_len, rope_theta=10000.0,
                         remat=False)


def _aux_loss(probs: jax.Array, top_idx: jax.Array,
              within_capacity: jax.Array, e: int, k: int) -> jax.Array:
    """Load-balancing aux loss (Switch/GShard): E * sum_e f_e * P_e.

    f counts only assignments that actually landed a capacity slot —
    identical for both dispatch implementations because both derive
    ``within_capacity`` from the same token-major priority order.
    """
    assigned = jnp.sum(jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
                       * within_capacity.astype(jnp.float32)[..., None],
                       axis=1)                                   # [T, E]
    f = jnp.mean(assigned, axis=0)                               # frac routed
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p) / k


class MoELayer(nn.Module):
    """Token-choice top-k routing with capacity.

    ``config.dispatch`` selects the routing implementation:
    ``"einsum"`` contracts one-hot [T,E,C] dispatch/combine tensors with
    dense einsums; ``"gather"`` routes by stable sort + gather/scatter.
    Both produce identical capacity drops, outputs, grads, and aux loss
    (pinned by tests/test_moe_dispatch.py). Dropped-assignment counts
    are sown into the "intermediates" collection as
    ``dropped_assignments`` when that collection is mutable.
    """

    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        if cfg.dispatch not in ("einsum", "gather"):
            raise ValueError(
                f"MixtralConfig.dispatch must be 'einsum' or 'gather', "
                f"got {cfg.dispatch!r}")
        b, s, h = x.shape
        t = b * s
        e = cfg.n_experts
        k = cfg.experts_per_token
        capacity = max(k, int(t * k * cfg.capacity_factor / e))
        if cfg.decode:
            # Inference never drops assignments: capacity dropping is a
            # training throughput/HBM trade, and it makes routing depend
            # on the rest of the batch — incremental decode could never
            # reproduce a full forward. At capacity = T*K no expert
            # buffer can overflow, so routing is per-token dense and
            # decode is exactly reproducible against a drop-free
            # reference (capacity_factor >= n_experts).
            capacity = t * k

        xt = x.reshape(t, h)
        router_logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                                 param_dtype=jnp.float32,
                                 name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]

        # top-k expert choice per token
        top_probs, top_idx = jax.lax.top_k(probs, k)             # [T, K]
        top_probs = top_probs / jnp.maximum(
            jnp.sum(top_probs, axis=-1, keepdims=True), 1e-9)

        # Expert params exist identically under either dispatch (same
        # names/shapes — checkpoints are interchangeable across modes).
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (e, h, cfg.mlp_dim), jnp.float32)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (e, h, cfg.mlp_dim), jnp.float32)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (e, cfg.mlp_dim, h), jnp.float32)

        def expert_ffn(expert_in: jax.Array) -> jax.Array:
            """Batched expert FFNs [E,C,H] -> [E,C,H] (leading dim e ->
            ep axis); shared verbatim by both dispatch paths."""
            gate = jnp.einsum("ech,ehm->ecm", expert_in,
                              w_gate.astype(cfg.dtype))
            up = jnp.einsum("ech,ehm->ecm", expert_in,
                            w_up.astype(cfg.dtype))
            act = nn.silu(gate) * up
            return jnp.einsum("ecm,emh->ech", act,
                              w_down.astype(cfg.dtype))          # [E,C,H]

        if cfg.dispatch == "gather":
            y, within_capacity = _gather_route(
                xt, top_idx, top_probs, capacity, expert_ffn, cfg)
        else:
            y, within_capacity = _einsum_route(
                xt, top_idx, top_probs, capacity, expert_ffn, cfg)
        y = y.reshape(b, s, h).astype(x.dtype)

        self.sow("intermediates", "dropped_assignments",
                 jnp.sum((~within_capacity).astype(jnp.int32)))
        aux = _aux_loss(probs, top_idx, within_capacity, e, k)
        return y, aux


def _einsum_route(xt, top_idx, top_probs, capacity, expert_ffn, cfg):
    """GShard one-hot dispatch: [T,E,C] routing tensors + dense einsums.

    O(T·E·C·H) matmul FLOPs and O(T·E·C) routing-tensor bytes per layer
    — the cost the gather path removes (docs/benchmarks.md MoE
    roofline).
    """
    t, h = xt.shape
    e, k = cfg.n_experts, cfg.experts_per_token
    x_dtype = xt.dtype

    # capacity positions: for each (token, k) assignment, this token's
    # slot is the count of earlier assignments choosing the same expert
    expert_onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.int32)  # [T,K,E]
    flat_assign = expert_onehot.reshape(t * k, e)
    position = (jnp.cumsum(flat_assign, axis=0) - flat_assign)    # [T*K,E]
    position = jnp.sum(position * flat_assign, axis=-1).reshape(t, k)
    within_capacity = position < capacity                    # [T, K]

    # dispatch [T, E, C] / combine [T, E, C]
    pos_onehot = jax.nn.one_hot(position, capacity,
                                dtype=x_dtype)               # [T,K,C]
    disp = (expert_onehot.astype(x_dtype)[..., None]
            * pos_onehot[:, :, None, :]
            * within_capacity.astype(x_dtype)[:, :, None, None])
    dispatch = jnp.sum(disp, axis=1)                         # [T,E,C]
    combine = jnp.sum(disp * top_probs.astype(x_dtype)[:, :, None, None],
                      axis=1)                                # [T,E,C]

    expert_in = jnp.einsum("tec,th->ech", dispatch, xt,
                           preferred_element_type=jnp.float32
                           ).astype(cfg.dtype)               # [E,C,H]
    expert_out = expert_ffn(expert_in)                       # [E,C,H]
    y = jnp.einsum("tec,ech->th", combine, expert_out)
    return y, within_capacity


def _gather_route(xt, top_idx, top_probs, capacity, expert_ffn, cfg):
    """Sort/gather dispatch: route tokens by a stable argsort on their
    expert choice, gather rows into the capacity-packed [E,C,H] buffers,
    and combine via a weighted inverse-permutation scatter.

    The stable sort preserves the (token-major, slot-minor) assignment
    order the einsum path's cumsum ranks by, so capacity positions —
    and therefore which assignments drop — are identical. Routing state
    is O(T·K) integers instead of O(T·E·C) floats, and the permutation
    costs gather/scatter bandwidth instead of matmul FLOPs.
    """
    from tf_operator_tpu.parallel.sharding import MOE_RULES, constrain

    t, h = xt.shape
    e, k = cfg.n_experts, cfg.experts_per_token
    x_dtype = xt.dtype
    tk = t * k

    flat_expert = top_idx.reshape(tk)                        # [T*K]
    order = jnp.argsort(flat_expert, stable=True)            # [T*K]
    sorted_expert = jnp.take(flat_expert, order)             # [T*K]
    # rank within expert = index - segment start (same count-of-earlier-
    # assignments the einsum path computes with its one-hot cumsum)
    counts = jnp.zeros((e,), jnp.int32).at[flat_expert].add(1)
    seg_start = jnp.cumsum(counts) - counts                  # [E]
    pos_sorted = (jnp.arange(tk, dtype=jnp.int32)
                  - jnp.take(seg_start, sorted_expert))      # [T*K]
    keep = pos_sorted < capacity                             # [T*K] (sorted)
    slot = sorted_expert * capacity + pos_sorted             # [T*K]
    src_tok = order // k                                     # [T*K]

    # dispatch: row-gather tokens into capacity-packed expert buffers;
    # over-capacity assignments scatter to an out-of-range slot and drop
    gathered = jnp.take(xt, src_tok, axis=0).astype(cfg.dtype)   # [T*K,H]
    expert_in = jnp.zeros((e * capacity, h), cfg.dtype).at[
        jnp.where(keep, slot, e * capacity)].set(
        gathered, mode="drop").reshape(e, capacity, h)       # [E,C,H]
    expert_in = constrain(expert_in, ("expert", "capacity", None),
                          MOE_RULES)
    expert_out = expert_ffn(expert_in)                       # [E,C,H]
    expert_out = constrain(expert_out, ("expert", "capacity", None),
                           MOE_RULES)

    # combine: weighted gather back through the inverse permutation,
    # then sum each token's K slot contributions
    out_rows = jnp.take(expert_out.reshape(e * capacity, h),
                        jnp.where(keep, slot, 0), axis=0)    # [T*K,H]
    w = jnp.take(top_probs.reshape(tk), order).astype(x_dtype)
    contrib = out_rows * jnp.where(keep, w, 0)[:, None]      # [T*K,H]
    unsorted = jnp.zeros((tk, contrib.shape[-1]),
                         contrib.dtype).at[order].set(contrib)
    y = jnp.sum(unsorted.reshape(t, k, -1), axis=1)          # [T,H]

    within_capacity = jnp.zeros((tk,), jnp.bool_).at[order].set(
        keep).reshape(t, k)
    return y, within_capacity


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array,
                 positions: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        x = x + LlamaAttention(cfg.attention_config(), name="attn")(
            RMSNorm(name="attn_norm")(x), angles, positions)
        moe_out, aux = MoELayer(cfg, name="moe")(RMSNorm(name="mlp_norm")(x))
        return x + moe_out, aux


class Mixtral(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array]:
        """Returns (logits, aux_loss)."""
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed_tokens")(tokens)
        angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                  cfg.rope_theta)

        block = MixtralBlock
        if cfg.remat and not cfg.decode:
            # Decode has no backward pass to trade HBM for; remat would
            # only re-run the forward.
            block = nn.remat(block, prevent_cse=False)
        variable_axes = {"params": 0}
        if cfg.decode:
            # Per-block KV caches stack on a leading layers axis, like
            # the scanned params (llama.py decode).
            variable_axes["cache"] = 0
        ScanBlocks = nn.scan(
            block,
            variable_axes=variable_axes,
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        if positions is None:
            x, aux = ScanBlocks(cfg, name="blocks")(x, angles)
        else:
            x, aux = ScanBlocks(cfg, name="blocks")(x, angles, positions)

        x = RMSNorm(name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits, jnp.mean(aux)


# ---------------------------------------------------------------------------
# Incremental decode (serving plane). Same contract as the Llama helpers
# (llama.py init_cache/prefill/decode_step/insert_cache) — the KV cache
# is an explicit pytree owned by the caller — except every forward
# returns (logits, aux); the helpers drop the aux loss (it only matters
# for training). insert_cache is the generic tree-map slot write and is
# re-exported from llama.py unchanged.
# ---------------------------------------------------------------------------

from tf_operator_tpu.models.llama import insert_cache  # noqa: E402,F401


def init_cache(model: "Mixtral", params, batch_size: int):
    """All-zeros KV cache pytree for ``batch_size`` concurrent slots
    (built from ``eval_shape``; see llama.init_cache)."""
    tokens = jnp.zeros((batch_size, 1), jnp.int32)
    positions = jnp.zeros((batch_size, 1), jnp.int32)
    _, variables = jax.eval_shape(
        lambda p, t, pos: model.apply({"params": p}, t, positions=pos,
                                      mutable=["cache"]),
        params, tokens, positions)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        variables["cache"])


def prefill(model: "Mixtral", params, cache, tokens: jax.Array,
            positions: jax.Array):
    """One incremental-decode forward: returns (logits, updated cache).
    The MoE aux loss is discarded (inference-only path)."""
    (logits, _aux), variables = model.apply(
        {"params": params, "cache": cache}, tokens, positions=positions,
        mutable=["cache"])
    return logits, variables["cache"]


def decode_step(model: "Mixtral", params, cache, tokens: jax.Array,
                positions: jax.Array):
    """One token per row: ``prefill`` at S = 1."""
    return prefill(model, params, cache, tokens, positions)


_MOE_LEAF_AXES = {
    ("router", "kernel"): ("embed", None),
    ("w_gate",): ("expert", "embed", "mlp"),
    ("w_up",): ("expert", "embed", "mlp"),
    ("w_down",): ("expert", "mlp", "embed"),
}


def param_logical_axes(path: Tuple[str, ...], value):
    """Mixtral logical axes: MoE params + the shared Llama mapping."""
    from tf_operator_tpu.models.llama import param_logical_axes as base_axes

    path = tuple(path)
    for suffix, axes in _MOE_LEAF_AXES.items():
        if path[-len(suffix):] == suffix:
            ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
            if len(axes) == ndim:
                return axes
            if len(axes) + 1 == ndim and "blocks" in path:
                return ("layers",) + axes
            break
    else:
        return base_axes(path, value)
    raise ValueError(f"no logical axes for MoE param {'/'.join(path)}")


def make_moe_lm_loss(aux_loss_weight: float = 0.02):
    """LM loss + weighted load-balancing aux loss."""
    from tf_operator_tpu.train.trainer import cross_entropy_loss

    def moe_lm_loss(params, extra_vars, batch, model_apply):
        tokens = batch["inputs"]
        logits, aux = model_apply({"params": params}, tokens[:, :-1])
        ce = cross_entropy_loss(logits, tokens[:, 1:], batch.get("mask"))
        return ce + aux * aux_loss_weight, extra_vars

    moe_lm_loss.model_inputs_fn = lambda b: (b["inputs"][:, :-1],)
    return moe_lm_loss
