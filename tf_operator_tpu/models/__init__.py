"""Model families for the BASELINE configs: MNIST, ResNet-50, BERT,
Llama (dense decoder), Mixtral (MoE decoder)."""
