"""ResNet-50 (BASELINE headline config: images/sec/chip on ImageNet).

Reference payload analog: the "ResNet-50/ImageNet TFJob, 1 Chief + 4
Workers (MultiWorkerMirroredStrategy)" baseline — rebuilt as a flax model
trained data-parallel under GSPMD (BN statistics become global-batch
statistics automatically; XLA inserts the dp all-reduces over ICI).

TPU notes: NHWC layout (XLA's preferred TPU conv layout), bfloat16
activations with f32 BN/params, bias-free convs before BN.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(num_classes=num_classes)


def resnet_tiny(num_classes: int = 10) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=num_classes)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=cfg.dtype,
                       param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        cfg = self.config
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
                    name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(cfg.width * (2 ** stage), strides, cfg,
                                    name=f"stage{stage}_block{block}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = x.astype(jnp.float32)
        return nn.Dense(cfg.num_classes, name="classifier",
                        param_dtype=jnp.float32)(x)


def param_logical_axes(path, value):
    """ResNet is pure data-parallel: params replicate (CNN_RULES)."""
    ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
    return (None,) * ndim


def synthetic_batch(rng: jax.Array, batch_size: int = 128,
                    image_size: int = 224, num_classes: int = 1000):
    kx, ky = jax.random.split(rng)
    return {
        "inputs": jax.random.uniform(kx, (batch_size, image_size, image_size, 3)),
        "labels": jax.random.randint(ky, (batch_size,), 0, num_classes),
    }
