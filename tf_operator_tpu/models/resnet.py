"""ResNet-50 (BASELINE headline config: images/sec/chip on ImageNet).

Reference payload analog: the "ResNet-50/ImageNet TFJob, 1 Chief + 4
Workers (MultiWorkerMirroredStrategy)" baseline — rebuilt as a flax model
trained data-parallel under GSPMD (BN statistics become global-batch
statistics automatically; XLA inserts the dp all-reduces over ICI).

TPU notes: NHWC layout (XLA's preferred TPU conv layout), bfloat16
activations with f32 BN/params, bias-free convs before BN.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    # Normalization scheme (measured trade-offs in docs/benchmarks.md):
    #   "bn"        batch norm, f32 statistics (flax-equivalent default)
    #   "bn_bf16"   batch norm with bf16 statistics accumulation
    #   "group"     GroupNorm(32) — no batch statistics, no running state
    #   "affine"    per-channel scale/bias only (frozen unit stats):
    #               throughput ceiling probe for norm-free schemes
    # "bn"/"bn_bf16" also support interval statistics: call the model
    # with update_stats=False to normalize with running stats (pure
    # affine, no reduces) — see Trainer stats_every_n.
    norm: str = "bn"
    # Stem form:
    #   "conv7"  classic 7x7/stride-2 conv on [N,224,224,3]
    #   "s2d"    space-to-depth: block-2 rearrange to [N,112,112,12]
    #            then a 4x4/stride-1 conv — mathematically the same
    #            function (see s2d_stem_kernel for the exact weight
    #            map), but MXU-shaped: the C=3 7x7 stride-2 conv is the
    #            profile's slowest op class (400-600 GB/s vs the 819
    #            HBM spec) because 3 input channels waste the systolic
    #            array's 128 lanes. The MLPerf-ResNet standard form.
    stem: str = "conv7"


def resnet50(num_classes: int = 1000, stem: str = "conv7") -> ResNetConfig:
    return ResNetConfig(num_classes=num_classes, stem=stem)


def resnet_tiny(num_classes: int = 10) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=num_classes)


def _norm_factory(cfg: ResNetConfig, train: bool, update_stats: bool):
    """Normalization layer constructor for the configured scheme.

    ``update_stats=False`` under "bn"/"bn_bf16" normalizes with running
    statistics (pure per-channel affine, zero reduces) — the interval-
    statistics building block.
    """
    from tf_operator_tpu.ops.layers import tpu_batch_norm

    common = dict(dtype=cfg.dtype, param_dtype=jnp.float32)
    if cfg.norm in ("bn", "bn_bf16"):
        stats = jnp.float32 if cfg.norm == "bn" else jnp.bfloat16
        return partial(tpu_batch_norm,
                       use_running_average=not (train and update_stats),
                       momentum=0.9, epsilon=1e-5, stats_dtype=stats,
                       **common)
    if cfg.norm == "group":
        return partial(_GroupNormAuto, dtype=cfg.dtype)
    if cfg.norm == "affine":
        return partial(tpu_batch_norm, use_running_average=True,
                       track_stats=False, epsilon=1e-5, **common)
    raise ValueError(f"unknown norm scheme {cfg.norm!r}")


class _GroupNormAuto(nn.Module):
    """GroupNorm with 32 groups, degrading gracefully on narrow layers
    (gcd with the channel count) so tiny test configs still build."""

    dtype: Any = jnp.bfloat16
    scale_init: Any = nn.initializers.ones

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        import math

        feat = x.shape[-1]
        groups = 32 if feat % 32 == 0 else math.gcd(32, feat)
        return nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                            dtype=self.dtype, param_dtype=jnp.float32,
                            scale_init=self.scale_init)(x)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True,
                 update_stats: bool = True) -> jax.Array:
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=jnp.float32)
        norm = _norm_factory(cfg, train, update_stats)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True,
                 update_stats: bool = True) -> jax.Array:
        cfg = self.config
        x = x.astype(cfg.dtype)
        if cfg.stem == "s2d":
            x = space_to_depth(x, 2)
            x = nn.Conv(cfg.width, (4, 4), strides=(1, 1),
                        padding=[(2, 1), (2, 1)], use_bias=False,
                        dtype=cfg.dtype, param_dtype=jnp.float32,
                        name="stem_conv_s2d")(x)
        else:
            x = nn.Conv(cfg.width, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)], use_bias=False,
                        dtype=cfg.dtype, param_dtype=jnp.float32,
                        name="stem_conv")(x)
        x = _norm_factory(cfg, train, update_stats)(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(cfg.width * (2 ** stage), strides, cfg,
                                    name=f"stage{stage}_block{block}")(
                                        x, train, update_stats)
        x = jnp.mean(x, axis=(1, 2))
        x = x.astype(jnp.float32)
        return nn.Dense(cfg.num_classes, name="classifier",
                        param_dtype=jnp.float32)(x)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[N, H, W, C] -> [N, H/b, W/b, C*b*b], channel order (bi, bj, c)
    i.e. out[n, i, j, (bi*b + bj)*C + c] = x[n, i*b + bi, j*b + bj, c].
    XLA lowers the reshape/transpose pair into the stem conv's input
    fusion, so the rearrange itself costs no extra HBM round-trip."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def s2d_stem_kernel(w7: jax.Array, block: int = 2) -> jax.Array:
    """Exact weight map: 7x7x3xO stride-2 kernel -> the 4x4x12xO
    stride-1 kernel that computes the SAME function on
    space_to_depth(x, 2) (the MLPerf-ResNet space-to-depth transform).

    Derivation: out(i) = sum_k W7[k] x[2i + k - 3]. Substitute
    k' = k + 1 (zero-pad the kernel front to 8): x[2i + k' - 4],
    then split k' = 2a + b with b in {0, 1}:
    x[2(i + a - 2) + b] = s2d(x)[i + a - 2, channel (b, c)] — a 4-tap
    stride-1 conv with padding (2, 1). Same for the second spatial dim.
    """
    kh, kw, cin, cout = w7.shape
    assert (kh, kw) == (7, 7), w7.shape
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    # [8, 8, C, O] -> [4, bi, 4, bj, C, O] -> [4, 4, (bi, bj, C), O]
    w4 = w8.reshape(4, block, 4, block, cin, cout)
    w4 = w4.transpose(0, 2, 1, 3, 4, 5)
    return w4.reshape(4, 4, block * block * cin, cout)


def param_logical_axes(path, value):
    """ResNet is pure data-parallel: params replicate (CNN_RULES)."""
    ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
    return (None,) * ndim


def synthetic_batch(rng: jax.Array, batch_size: int = 128,
                    image_size: int = 224, num_classes: int = 1000):
    kx, ky = jax.random.split(rng)
    return {
        "inputs": jax.random.uniform(kx, (batch_size, image_size, image_size, 3)),
        "labels": jax.random.randint(ky, (batch_size,), 0, num_classes),
    }
