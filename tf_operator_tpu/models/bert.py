"""BERT-family encoder (BASELINE config: "BERT-base pretraining TFJob,
PS + 8 Workers with gang scheduling").

Bidirectional transformer encoder with an MLM head, same scan-over-layers
TPU structure as the decoder families. MLM batches carry
``inputs``/``targets``/``mask`` (masked positions only).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.ops.layers import attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    head_dim: int = 64
    mlp_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16
    remat: bool = True


def bert_base() -> BertConfig:
    return BertConfig()


def bert_tiny(vocab_size: int = 256, max_seq_len: int = 128) -> BertConfig:
    return BertConfig(vocab_size=vocab_size, hidden=64, n_layers=2,
                      n_heads=4, head_dim=16, mlp_dim=128,
                      max_seq_len=max_seq_len, remat=False)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array,
                 attn_mask: Optional[jax.Array]) -> jax.Array:
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        b, s, _ = x.shape
        q = dense(cfg.n_heads * cfg.head_dim, "wq")(x)
        k = dense(cfg.n_heads * cfg.head_dim, "wk")(x)
        v = dense(cfg.n_heads * cfg.head_dim, "wv")(x)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
        mask = None
        if attn_mask is not None:
            mask = attn_mask[:, None, None, :].astype(bool)  # [B,1,1,S]
        out = attention(q, k, v, causal=False, mask=mask)
        out = out.reshape(b, s, cfg.n_heads * cfg.head_dim)
        return dense(cfg.hidden, "wo")(out)


class BertBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, attn_mask: Optional[jax.Array]
                 ) -> Tuple[jax.Array, None]:
        cfg = self.config
        ln = lambda name: nn.LayerNorm(dtype=cfg.dtype,
                                       param_dtype=jnp.float32, name=name)
        x = ln("attn_ln")(x + BertSelfAttention(cfg, name="attn")(x, attn_mask))
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_in")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_out")(h)
        x = ln("mlp_ln")(x + h)
        return x, None


class Bert(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 attn_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        b, s = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed_tokens")(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_seq_len, cfg.hidden), jnp.float32)
        x = x + pos[None, :s].astype(cfg.dtype)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="embed_ln")(x)

        block = BertBlock
        if cfg.remat:
            block = nn.remat(block, prevent_cse=False)
        ScanBlocks = nn.scan(
            block,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = ScanBlocks(cfg, name="blocks")(x, attn_mask)

        # MLM head: transform + tied-free output projection
        x = nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlm_transform")(x)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="mlm_ln")(x)
        return nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name="mlm_head")(x)


_LEAF_AXES = {
    ("embed_tokens", "embedding"): ("vocab", "embed"),
    ("pos_embed",): ("seq", "embed"),
    ("wq", "kernel"): ("embed", "heads"),
    ("wk", "kernel"): ("embed", "heads"),
    ("wv", "kernel"): ("embed", "heads"),
    ("wo", "kernel"): ("heads", "embed"),
    ("mlp_in", "kernel"): ("embed", "mlp"),
    ("mlp_out", "kernel"): ("mlp", "embed"),
    # both dims are embed-sized; shard only one (an axis may appear once)
    ("mlm_transform", "kernel"): ("embed", None),
    ("mlm_head", "kernel"): ("embed", "vocab"),
}


def param_logical_axes(path: Tuple[str, ...], value):
    path = tuple(path)
    ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
    for suffix, axes in _LEAF_AXES.items():
        if path[-len(suffix):] == suffix:
            if len(axes) == ndim:
                return axes
            if len(axes) + 1 == ndim and "blocks" in path:
                return ("layers",) + axes
            break
    # biases, LayerNorm scales: replicate
    if ndim <= 2:
        return (None,) * ndim
    raise ValueError(f"no logical axes for BERT param {'/'.join(path)}")


def mlm_loss(params, extra_vars, batch, model_apply):
    """Masked-LM loss over masked positions only."""
    from tf_operator_tpu.train.trainer import cross_entropy_loss

    logits = model_apply({"params": params}, batch["inputs"],
                         batch.get("attn_mask"))
    return cross_entropy_loss(logits, batch["targets"],
                              batch.get("mask")), extra_vars


mlm_loss.model_inputs_fn = lambda b: (b["inputs"], b.get("attn_mask"))
