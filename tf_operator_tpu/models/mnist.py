"""MNIST models (BASELINE config: "MNIST TFJob, 1 Worker (CPU, no PS)").

Reference payload analog: examples/v1/dist-mnist/dist_mnist.py and
examples/v1/mnist_with_summaries. A small CNN + a pure-MLP variant.
"""

from __future__ import annotations

import flax.linen as nn
import jax


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # x: [B, 28, 28, 1] float32 in [0, 1]
        x = nn.Conv(32, (5, 5), padding="SAME", name="conv1")(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(64, (5, 5), padding="SAME", name="conv2")(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(512, name="fc1")(x))
        return nn.Dense(self.num_classes, name="fc2")(x)


class MnistMLP(nn.Module):
    num_classes: int = 10
    hidden: int = 128

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(self.hidden, name="fc1")(x))
        return nn.Dense(self.num_classes, name="fc2")(x)


def synthetic_batch(rng: jax.Array, batch_size: int = 64):
    kx, ky = jax.random.split(rng)
    return {
        "inputs": jax.random.uniform(kx, (batch_size, 28, 28, 1)),
        "labels": jax.random.randint(ky, (batch_size,), 0, 10),
    }
