"""Llama-family dense decoder (the BASELINE "Llama-3 8B JAX/SPMD" config).

TPU-first structure:
- layers are stacked with ``nn.scan`` + ``nn.remat`` — one compiled block
  body regardless of depth (fast XLA compiles) with rematerialized
  activations (HBM for FLOPs trade);
- bfloat16 activations, float32 params/accumulation;
- attention can run as ring attention over the ``sp`` mesh axis for long
  context (context parallelism), or plain (to be fused by XLA / pallas);
- params carry no sharding metadata — logical axes are assigned by
  ``param_logical_axes`` (path-based), keeping the model mesh-agnostic
  (rules tables in parallel/sharding.py decide dp/fsdp/tp placement).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.compat import shard_map

from tf_operator_tpu.ops.layers import (
    apply_rope,
    attention,
    repeat_kv,
    rms_norm,
    rope_frequencies,
)
from tf_operator_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Remat granularity (round-5 roofline: the flash kernel's forward
    # re-executes inside the backward scan under whole-block remat —
    # profile_llama.py measured it at ~7% of the step):
    #   "full"     — rematerialize the whole block (lowest memory);
    #   "save_attn"— remat the whole block but save the flash kernel's
    #                named outputs (flash_out/flash_lse): the backward
    #                pass reuses them instead of re-running the kernel
    #                (~65 MB/layer at the 570M bench shape — fits where
    #                mlp_only OOMs). The names only exist on the flash
    #                path: with attention_impl="xla"/"ring" nothing is
    #                saved and this degrades to "full";
    #   "save_qkv" — save_attn plus the post-rope q/k/v projections
    #                (attn_q/k/v, ~96 MB/layer at MHA): the backward
    #                also skips the QKV matmul + rope recompute;
    #   "mlp_only" — remat only the MLP branch; the attention branch
    #                runs un-remat'd so the flash custom-vjp residuals
    #                (q,k,v,out,lse) persist to the backward pass and
    #                neither the kernel nor the QKV/rope path is
    #                recomputed. Costs ~200 MB/layer at the 570M bench
    #                shape; wins when HBM allows.
    remat_policy: str = "full"
    # "" = auto (pallas flash on TPU when shapes tile, else XLA);
    # "flash" = force the pallas kernel; "xla" = force the reference;
    # "ring" = einsum ring attention over sp; "ring_flash" = ring with
    # the pallas flash kernel per block (preferred when block shapes
    # tile; both ring modes run inside shard_map, which the trainer
    # arranges when sp > 1).
    attention_impl: str = ""
    sp_axis: str = "sp"
    # Incremental-decode mode (the serving plane, tf_operator_tpu/serve):
    # attention reads/writes a causal KV cache ("cache" collection,
    # [batch, kv_seq=max_seq_len, kv_heads, head_dim], constrained to
    # the mesh via parallel/sharding.py logical axes) instead of
    # recomputing the whole prefix. __call__ then REQUIRES per-token
    # ``positions`` and the caller must thread the cache through
    # ``mutable=["cache"]`` (see prefill/decode_step below). The param
    # tree is identical to the training model's, so trained checkpoints
    # load unchanged; remat is bypassed (no backward pass to trade for).
    decode: bool = False


def llama_3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama_tiny(vocab_size: int = 256, max_seq_len: int = 128) -> LlamaConfig:
    return LlamaConfig(vocab_size=vocab_size, hidden=64, n_layers=2,
                       n_heads=4, n_kv_heads=2, head_dim=16, mlp_dim=128,
                       max_seq_len=max_seq_len, rope_theta=10000.0,
                       remat=False)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        b, s, _ = x.shape
        q = dense(cfg.n_heads * cfg.head_dim, "wq")(x)
        k = dense(cfg.n_kv_heads * cfg.head_dim, "wk")(x)
        v = dense(cfg.n_kv_heads * cfg.head_dim, "wv")(x)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)

        # RoPE on the global sequence view (GSPMD handles the sharding;
        # ring blocks only materialize inside the shard_map region below).
        # ``positions`` ([B, S] absolute token positions) only on the
        # decode path — each row rotates at its own sequence offset.
        q = apply_rope(q, angles, positions)
        k = apply_rope(k, angles, positions)
        if cfg.decode:
            if positions is None:
                raise ValueError("decode mode requires positions")
            return dense(cfg.hidden, "wo")(
                self._cached_attention(q, k, v, positions)
                .reshape(b, s, cfg.n_heads * cfg.head_dim))
        # Saveable under remat_policy="save_qkv": keeps the post-rope
        # projections across the remat boundary so the backward pass
        # skips the QKV matmuls + rope recompute (no-op otherwise).
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        if cfg.attention_impl in ("ring", "xla"):
            # These paths need full-head KV; the flash kernels (incl.
            # ring_flash) read the shared GQA head directly (no repeated
            # copy in HBM).
            k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
            v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)

        if cfg.attention_impl in ("ring", "ring_flash"):
            from tf_operator_tpu.parallel.mesh import active_mesh, data_axes
            from jax.sharding import PartitionSpec as P
            import functools

            from tf_operator_tpu.ops.ring_attention import (
                ring_flash_attention,
            )

            mesh = active_mesh()
            if mesh is None:
                raise ValueError("ring attention requires an active mesh "
                                 "(wrap the step in parallel.mesh.use_mesh)")
            tp_size = mesh.shape.get("tp", 1)
            if (cfg.attention_impl == "ring_flash"
                    and k.shape[2] % max(tp_size, 1)):
                # The head spec shards KV heads over tp; when tp does
                # not divide the GQA head count, fall back to full-head
                # KV (the kernel's native-GQA saving doesn't apply, but
                # the sharding is well-formed).
                k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
                v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
            spec = P(data_axes(mesh), cfg.sp_axis,
                     "tp" if "tp" in mesh.axis_names else None, None)
            inner = (ring_flash_attention
                     if cfg.attention_impl == "ring_flash"
                     else ring_attention)
            out = shard_map(
                functools.partial(inner, axis_name=cfg.sp_axis,
                                  causal=True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False)(q, k, v)
        elif cfg.attention_impl == "xla":
            out = attention(q, k, v, causal=True)
        else:  # "" = auto, "flash" = force the pallas kernel
            from tf_operator_tpu.ops.flash_attention import best_attention
            from tf_operator_tpu.parallel.mesh import active_mesh
            out = best_attention(q, k, v, causal=True, mesh=active_mesh(),
                                 force_flash=cfg.attention_impl == "flash")

        out = out.reshape(b, s, cfg.n_heads * cfg.head_dim)
        return dense(cfg.hidden, "wo")(out)

    def _cached_attention(self, q: jax.Array, k: jax.Array, v: jax.Array,
                          positions: jax.Array) -> jax.Array:
        """Incremental attention against the causal KV cache.

        The cache is [B, max_seq_len, n_kv_heads, head_dim] per block
        ("cache" collection; the scan stacks a leading layers axis).
        ``positions`` [B, S] are the absolute positions of this call's
        tokens — consecutive per row by contract — so the new K/V land
        at rows [positions[:,0], positions[:,0]+S) and a row attends
        exactly the key positions <= its own. Rows past a sequence's
        length are never attended (they are overwritten at the position
        that first attends them), which is what makes slot reuse and
        padded prefill safe for the continuous batcher (serve/batcher).
        """
        from tf_operator_tpu.parallel.sharding import LLAMA_RULES, constrain

        cfg = self.config
        b, s = q.shape[0], q.shape[1]
        shape = (b, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)
        cache_k = self.variable("cache", "k", jnp.zeros, shape, cfg.dtype)
        cache_v = self.variable("cache", "v", jnp.zeros, shape, cfg.dtype)
        start = positions[:, 0]

        def put(cache, new, p):
            return jax.lax.dynamic_update_slice(cache, new, (p, 0, 0))

        kv_axes = ("batch", "kv_seq", "kv_heads", "head_dim")
        new_k = constrain(jax.vmap(put)(cache_k.value, k.astype(cfg.dtype),
                                        start), kv_axes, LLAMA_RULES)
        new_v = constrain(jax.vmap(put)(cache_v.value, v.astype(cfg.dtype),
                                        start), kv_axes, LLAMA_RULES)
        cache_k.value = new_k
        cache_v.value = new_v

        n_rep = cfg.n_heads // cfg.n_kv_heads
        keys = repeat_kv(new_k, n_rep)
        vals = repeat_kv(new_v, n_rep)
        k_pos = jnp.arange(cfg.max_seq_len)
        # [B, 1, S, T]: broadcasts over heads in attention()'s logits.
        mask = k_pos[None, None, None, :] <= positions[:, None, :, None]
        return attention(q, keys, vals, causal=False, mask=mask)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        gate = dense(cfg.mlp_dim, "gate")(x)
        up = dense(cfg.mlp_dim, "up")(x)
        return dense(cfg.hidden, "down")(nn.silu(gate) * up)


class RMSNorm(nn.Module):
    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        return rms_norm(x, scale)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array,
                 positions: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, None]:
        x = x + LlamaAttention(self.config, name="attn")(
            RMSNorm(name="attn_norm")(x), angles, positions)
        x = x + LlamaMLP(self.config, name="mlp")(
            RMSNorm(name="mlp_norm")(x))
        return x, None


class LlamaBlockMlpRemat(nn.Module):
    """LlamaBlock with remat scoped to the MLP branch only (config
    remat_policy="mlp_only"): same parameter tree — module names match
    LlamaBlock's, so param_logical_axes and checkpoints are
    interchangeable — but the attention branch keeps its activations
    (incl. the flash kernel's residuals), trading HBM for not running
    the attention forward twice."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array,
                 positions: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, None]:
        x = x + LlamaAttention(self.config, name="attn")(
            RMSNorm(name="attn_norm")(x), angles, positions)
        mlp = nn.remat(LlamaMLP, prevent_cse=False)
        x = x + mlp(self.config, name="mlp")(
            RMSNorm(name="mlp_norm")(x))
        return x, None


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed_tokens")(tokens)
        angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                  cfg.rope_theta)

        block = LlamaBlock
        if cfg.remat and not cfg.decode:
            if cfg.remat_policy == "mlp_only":
                block = LlamaBlockMlpRemat
            elif cfg.remat_policy in ("save_attn", "save_qkv"):
                names = ["flash_out", "flash_lse"]
                if cfg.remat_policy == "save_qkv":
                    names += ["attn_q", "attn_k", "attn_v"]
                block = nn.remat(
                    block, prevent_cse=False,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        *names))
            elif cfg.remat_policy != "full":
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}; expected "
                    "full | save_attn | save_qkv | mlp_only")
            else:
                block = nn.remat(block, prevent_cse=False)
        variable_axes = {"params": 0}
        if cfg.decode:
            # Per-block KV caches stack on a leading layers axis, like
            # the scanned params.
            variable_axes["cache"] = 0
        ScanBlocks = nn.scan(
            block,
            variable_axes=variable_axes,
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        if positions is None:
            x, _ = ScanBlocks(cfg, name="blocks")(x, angles)
        else:
            x, _ = ScanBlocks(cfg, name="blocks")(x, angles, positions)

        x = RMSNorm(name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits


# ---------------------------------------------------------------------------
# Logical axes (consumed by parallel/sharding.py rule tables)
# ---------------------------------------------------------------------------

_LEAF_AXES = {
    ("embed_tokens", "embedding"): ("vocab", "embed"),
    ("wq", "kernel"): ("embed", "heads"),
    ("wk", "kernel"): ("embed", "kv_heads"),
    ("wv", "kernel"): ("embed", "kv_heads"),
    ("wo", "kernel"): ("heads", "embed"),
    ("gate", "kernel"): ("embed", "mlp"),
    ("up", "kernel"): ("embed", "mlp"),
    ("down", "kernel"): ("mlp", "embed"),
    ("lm_head", "kernel"): ("embed", "vocab"),
    ("scale",): ("norm",),
}


def param_logical_axes(path: Tuple[str, ...], value) -> Tuple[Optional[str], ...]:
    """Map a param path (flax dict path) to logical axis names; scanned
    block params get a leading "layers" axis."""
    path = tuple(path)
    for suffix, axes in _LEAF_AXES.items():
        if path[-len(suffix):] == suffix:
            ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
            if len(axes) == ndim:
                return axes
            if len(axes) + 1 == ndim and "blocks" in path:
                return ("layers",) + axes
            break
    raise ValueError(f"no logical axes for param {'/'.join(path)} "
                     f"shape {getattr(value, 'shape', '?')}")


# ---------------------------------------------------------------------------
# Incremental decode (serving plane). The KV cache is an explicit pytree
# threaded through flax's mutable-collection mechanism so the caller (the
# continuous batcher) owns slot management:
#
#   model  = Llama(dataclasses.replace(cfg, decode=True))   # same params
#   cache  = init_cache(model, params, batch_size=slots)
#   logits, c1 = prefill(model, params, one_cache, prompt, positions)
#   cache  = insert_cache(cache, c1, slot)                  # slot admission
#   logits, cache = decode_step(model, params, cache, tok, positions)
#
# All four are jittable (positions/slot may be traced). Cache leaves are
# [layers, batch, kv_seq, kv_heads, head_dim]; inside the model each
# block constrains its slice to the mesh via the kv_heads/kv_seq logical
# axes (parallel/sharding.py LLAMA_RULES), so tp shards cache heads
# exactly like the attention weights.
# ---------------------------------------------------------------------------


def init_cache(model: "Llama", params, batch_size: int):
    """All-zeros KV cache pytree for ``batch_size`` concurrent slots.

    Built from ``eval_shape`` (never a traced dummy forward), so no
    garbage key/value ever enters the cache: a slot's rows are only ever
    written by prefill/decode_step at the positions that later attend
    them."""
    tokens = jnp.zeros((batch_size, 1), jnp.int32)
    positions = jnp.zeros((batch_size, 1), jnp.int32)
    _, variables = jax.eval_shape(
        lambda p, t, pos: model.apply({"params": p}, t, positions=pos,
                                      mutable=["cache"]),
        params, tokens, positions)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        variables["cache"])


def prefill(model: "Llama", params, cache, tokens: jax.Array,
            positions: jax.Array):
    """One incremental-decode forward: returns (logits, updated cache).

    ``tokens``/``positions`` are [B, S]; each row's positions must be
    consecutive (its KV rows land at [positions[i,0],
    positions[i,0]+S)). Prompt processing uses S = prompt length (pad
    tails are harmless — see LlamaAttention._cached_attention); decoding
    is the same call at S = 1."""
    logits, variables = model.apply({"params": params, "cache": cache},
                                    tokens, positions=positions,
                                    mutable=["cache"])
    return logits, variables["cache"]


def decode_step(model: "Llama", params, cache, tokens: jax.Array,
                positions: jax.Array):
    """One token per row: ``prefill`` at S = 1 (separate name so call
    sites read as the phase they implement)."""
    return prefill(model, params, cache, tokens, positions)


def insert_cache(cache, one, slot):
    """Write a 1-row cache (a finished prefill) into ``slot`` of the
    decode cache — the continuous batcher's slot-admission primitive.
    ``slot`` may be traced; leaves are [layers, batch, kv_seq, ...], so
    the batch axis is 1."""
    return jax.tree.map(
        lambda c, o: jax.lax.dynamic_update_slice_in_dim(
            c, o.astype(c.dtype), slot, axis=1), cache, one)
