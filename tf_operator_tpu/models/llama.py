"""Llama-family dense decoder (the BASELINE "Llama-3 8B JAX/SPMD" config).

TPU-first structure:
- layers are stacked with ``nn.scan`` + ``nn.remat`` — one compiled block
  body regardless of depth (fast XLA compiles) with rematerialized
  activations (HBM for FLOPs trade);
- bfloat16 activations, float32 params/accumulation;
- attention can run as ring attention over the ``sp`` mesh axis for long
  context (context parallelism), or plain (to be fused by XLA / pallas);
- params carry no sharding metadata — logical axes are assigned by
  ``param_logical_axes`` (path-based), keeping the model mesh-agnostic
  (rules tables in parallel/sharding.py decide dp/fsdp/tp placement).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.compat import shard_map

from tf_operator_tpu.ops.layers import (
    apply_rope,
    attention,
    repeat_kv,
    rms_norm,
    rope_frequencies,
)
from tf_operator_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Remat granularity (round-5 roofline: the flash kernel's forward
    # re-executes inside the backward scan under whole-block remat —
    # profile_llama.py measured it at ~7% of the step):
    #   "full"     — rematerialize the whole block (lowest memory);
    #   "save_attn"— remat the whole block but save the flash kernel's
    #                named outputs (flash_out/flash_lse): the backward
    #                pass reuses them instead of re-running the kernel
    #                (~65 MB/layer at the 570M bench shape — fits where
    #                mlp_only OOMs). The names only exist on the flash
    #                path: with attention_impl="xla"/"ring" nothing is
    #                saved and this degrades to "full";
    #   "save_qkv" — save_attn plus the post-rope q/k/v projections
    #                (attn_q/k/v, ~96 MB/layer at MHA): the backward
    #                also skips the QKV matmul + rope recompute;
    #   "mlp_only" — remat only the MLP branch; the attention branch
    #                runs un-remat'd so the flash custom-vjp residuals
    #                (q,k,v,out,lse) persist to the backward pass and
    #                neither the kernel nor the QKV/rope path is
    #                recomputed. Costs ~200 MB/layer at the 570M bench
    #                shape; wins when HBM allows.
    remat_policy: str = "full"
    # "" = auto (pallas flash on TPU when shapes tile, else XLA);
    # "flash" = force the pallas kernel; "xla" = force the reference;
    # "ring" = einsum ring attention over sp; "ring_flash" = ring with
    # the pallas flash kernel per block (preferred when block shapes
    # tile; both ring modes run inside shard_map, which the trainer
    # arranges when sp > 1).
    attention_impl: str = ""
    sp_axis: str = "sp"


def llama_3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama_tiny(vocab_size: int = 256, max_seq_len: int = 128) -> LlamaConfig:
    return LlamaConfig(vocab_size=vocab_size, hidden=64, n_layers=2,
                       n_heads=4, n_kv_heads=2, head_dim=16, mlp_dim=128,
                       max_seq_len=max_seq_len, rope_theta=10000.0,
                       remat=False)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        b, s, _ = x.shape
        q = dense(cfg.n_heads * cfg.head_dim, "wq")(x)
        k = dense(cfg.n_kv_heads * cfg.head_dim, "wk")(x)
        v = dense(cfg.n_kv_heads * cfg.head_dim, "wv")(x)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)

        # RoPE on the global sequence view (GSPMD handles the sharding;
        # ring blocks only materialize inside the shard_map region below).
        q = apply_rope(q, angles)
        k = apply_rope(k, angles)
        # Saveable under remat_policy="save_qkv": keeps the post-rope
        # projections across the remat boundary so the backward pass
        # skips the QKV matmuls + rope recompute (no-op otherwise).
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        if cfg.attention_impl in ("ring", "xla"):
            # These paths need full-head KV; the flash kernels (incl.
            # ring_flash) read the shared GQA head directly (no repeated
            # copy in HBM).
            k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
            v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)

        if cfg.attention_impl in ("ring", "ring_flash"):
            from tf_operator_tpu.parallel.mesh import active_mesh, data_axes
            from jax.sharding import PartitionSpec as P
            import functools

            from tf_operator_tpu.ops.ring_attention import (
                ring_flash_attention,
            )

            mesh = active_mesh()
            if mesh is None:
                raise ValueError("ring attention requires an active mesh "
                                 "(wrap the step in parallel.mesh.use_mesh)")
            tp_size = mesh.shape.get("tp", 1)
            if (cfg.attention_impl == "ring_flash"
                    and k.shape[2] % max(tp_size, 1)):
                # The head spec shards KV heads over tp; when tp does
                # not divide the GQA head count, fall back to full-head
                # KV (the kernel's native-GQA saving doesn't apply, but
                # the sharding is well-formed).
                k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
                v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
            spec = P(data_axes(mesh), cfg.sp_axis,
                     "tp" if "tp" in mesh.axis_names else None, None)
            inner = (ring_flash_attention
                     if cfg.attention_impl == "ring_flash"
                     else ring_attention)
            out = shard_map(
                functools.partial(inner, axis_name=cfg.sp_axis,
                                  causal=True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False)(q, k, v)
        elif cfg.attention_impl == "xla":
            out = attention(q, k, v, causal=True)
        else:  # "" = auto, "flash" = force the pallas kernel
            from tf_operator_tpu.ops.flash_attention import best_attention
            from tf_operator_tpu.parallel.mesh import active_mesh
            out = best_attention(q, k, v, causal=True, mesh=active_mesh(),
                                 force_flash=cfg.attention_impl == "flash")

        out = out.reshape(b, s, cfg.n_heads * cfg.head_dim)
        return dense(cfg.hidden, "wo")(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name)
        gate = dense(cfg.mlp_dim, "gate")(x)
        up = dense(cfg.mlp_dim, "up")(x)
        return dense(cfg.hidden, "down")(nn.silu(gate) * up)


class RMSNorm(nn.Module):
    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        return rms_norm(x, scale)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array
                 ) -> Tuple[jax.Array, None]:
        x = x + LlamaAttention(self.config, name="attn")(
            RMSNorm(name="attn_norm")(x), angles)
        x = x + LlamaMLP(self.config, name="mlp")(
            RMSNorm(name="mlp_norm")(x))
        return x, None


class LlamaBlockMlpRemat(nn.Module):
    """LlamaBlock with remat scoped to the MLP branch only (config
    remat_policy="mlp_only"): same parameter tree — module names match
    LlamaBlock's, so param_logical_axes and checkpoints are
    interchangeable — but the attention branch keeps its activations
    (incl. the flash kernel's residuals), trading HBM for not running
    the attention forward twice."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, angles: jax.Array
                 ) -> Tuple[jax.Array, None]:
        x = x + LlamaAttention(self.config, name="attn")(
            RMSNorm(name="attn_norm")(x), angles)
        mlp = nn.remat(LlamaMLP, prevent_cse=False)
        x = x + mlp(self.config, name="mlp")(
            RMSNorm(name="mlp_norm")(x))
        return x, None


class Llama(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed_tokens")(tokens)
        angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                  cfg.rope_theta)

        block = LlamaBlock
        if cfg.remat:
            if cfg.remat_policy == "mlp_only":
                block = LlamaBlockMlpRemat
            elif cfg.remat_policy in ("save_attn", "save_qkv"):
                names = ["flash_out", "flash_lse"]
                if cfg.remat_policy == "save_qkv":
                    names += ["attn_q", "attn_k", "attn_v"]
                block = nn.remat(
                    block, prevent_cse=False,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        *names))
            elif cfg.remat_policy != "full":
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}; expected "
                    "full | save_attn | save_qkv | mlp_only")
            else:
                block = nn.remat(block, prevent_cse=False)
        ScanBlocks = nn.scan(
            block,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = ScanBlocks(cfg, name="blocks")(x, angles)

        x = RMSNorm(name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits


# ---------------------------------------------------------------------------
# Logical axes (consumed by parallel/sharding.py rule tables)
# ---------------------------------------------------------------------------

_LEAF_AXES = {
    ("embed_tokens", "embedding"): ("vocab", "embed"),
    ("wq", "kernel"): ("embed", "heads"),
    ("wk", "kernel"): ("embed", "kv_heads"),
    ("wv", "kernel"): ("embed", "kv_heads"),
    ("wo", "kernel"): ("heads", "embed"),
    ("gate", "kernel"): ("embed", "mlp"),
    ("up", "kernel"): ("embed", "mlp"),
    ("down", "kernel"): ("mlp", "embed"),
    ("lm_head", "kernel"): ("embed", "vocab"),
    ("scale",): ("norm",),
}


def param_logical_axes(path: Tuple[str, ...], value) -> Tuple[Optional[str], ...]:
    """Map a param path (flax dict path) to logical axis names; scanned
    block params get a leading "layers" axis."""
    path = tuple(path)
    for suffix, axes in _LEAF_AXES.items():
        if path[-len(suffix):] == suffix:
            ndim = value.ndim if hasattr(value, "ndim") else len(value.shape)
            if len(axes) == ndim:
                return axes
            if len(axes) + 1 == ndim and "blocks" in path:
                return ("layers",) + axes
            break
    raise ValueError(f"no logical axes for param {'/'.join(path)} "
                     f"shape {getattr(value, 'shape', '?')}")
