"""tf_operator_tpu — a TPU-native distributed-job orchestration framework.

A ground-up rebuild of the capabilities of the Kubeflow TF-Operator
(reference: /root/reference, a Go Kubernetes operator) designed TPU-first:

- A declarative ``TPUJob`` API (replica roles, slice topology, run policy)
  mirroring the TFJob CRD surface (reference ``pkg/apis/tensorflow/v1/types.go``).
- A generic level-triggered reconcile engine with expectations, adoption and
  index-stable replica identity (reference ``vendor/.../kubeflow/common``).
- TPU cluster bootstrap: slice topology -> ICI mesh axes -> per-worker env
  (``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``/coordinator), replacing the
  reference's TF_CONFIG rendering (``pkg/controller.v1/tensorflow/tensorflow.go``).
- An in-repo JAX/pjit/pallas training harness (data/tensor/expert/context
  parallel model families) that the reference delegated to user containers.
"""

from tf_operator_tpu.version import __version__, GIT_SHA  # noqa: F401
