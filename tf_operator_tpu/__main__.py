from tf_operator_tpu.cli import main

raise SystemExit(main())
