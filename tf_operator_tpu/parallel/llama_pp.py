"""Pipeline-parallel training step for the Llama decoder.

The model's decoder blocks are already scan-stacked (params carry a
leading [n_layers] axis, models/llama.py ScanBlocks), so pipelining is a
reshape, not a rewrite: [L, ...] leaves become [pp, L/pp, ...] stage
stacks, each 1F1B stage scans its L/pp layers, the embedding closes
through stage-0 input cotangents, and final-norm + lm_head ride the
last-stage loss head (parallel/pipeline.py pipeline_lm_train_sharded).

The reference has no pipeline parallelism at all (SURVEY §2.3); this is
the TPU-native composition: pp over ICI ring hops, dp/fsdp over the
remaining axes, exact gradients for every parameter group.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.models.llama import (
    Llama,
    LlamaBlock,
    LlamaConfig,
    RMSNorm,
)
from tf_operator_tpu.ops.layers import rope_frequencies
from tf_operator_tpu.parallel.pipeline import pipeline_lm_train_sharded


def split_stage_params(block_params: Any, pp: int) -> Any:
    """[L, ...] scan-stacked block params -> [pp, L/pp, ...] stages."""
    def reshape(p):
        if p.shape[0] % pp:
            raise ValueError(
                f"n_layers {p.shape[0]} not divisible by pp={pp}")
        return p.reshape((pp, p.shape[0] // pp) + p.shape[1:])

    return jax.tree_util.tree_map(reshape, block_params)


def merge_stage_params(stacked: Any) -> Any:
    """[pp, L/pp, ...] -> [L, ...] (back to the model's layout)."""
    return jax.tree_util.tree_map(
        lambda p: p.reshape((p.shape[0] * p.shape[1],) + p.shape[2:]),
        stacked)


def llama_pp_loss_and_grads(cfg: LlamaConfig, params: Dict[str, Any],
                            tokens: jax.Array, mesh,
                            num_microbatches: int,
                            axis_name: str = "pp",
                            staged: bool = False,
                            schedule: str = "1f1b"
                            ) -> Tuple[jax.Array, Dict[str, Any]]:
    """One pipeline-parallel LM loss+grad evaluation.

    ``params`` is the model's own tree (embed_tokens / blocks /
    final_norm / lm_head); ``tokens`` is the [B, T+1] next-token batch
    (the usual lm_loss contract). Returns (mean loss, grads in the same
    tree layout as ``params``) — compose with any optax optimizer.

    ``staged=True`` means ``params["blocks"]`` already carries the
    [pp, L/pp, ...] stage layout (the pipeline trainer's canonical form)
    and gradients come back in it too — no reshape round-trips.
    """
    pp = mesh.shape[axis_name]
    angles = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                              cfg.rope_theta)
    stacked = (params["blocks"] if staged
               else split_stage_params(params["blocks"], pp))
    embed_params = {"embed_tokens": params["embed_tokens"]}
    head_params = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}
    inputs = tokens[:, :-1]
    targets = tokens[:, 1:]

    block = LlamaBlock(cfg)

    def stage_fn(stage_params, x):
        # x: [mb, T, hidden]; scan this stage's L/pp layers.
        def one(carry, layer_params):
            y, _ = block.apply({"params": layer_params}, carry, angles)
            return y, None

        y, _ = jax.lax.scan(one, x, stage_params)
        return y

    def embed_fn(ep, tok_mb):
        # flax nn.Embed lookup, functionally: [m, mb, T] -> [m, mb, T, H]
        table = ep["embed_tokens"]["embedding"]
        return table[tok_mb].astype(cfg.dtype)

    def loss_fn(y, t_mb, hp):
        from tf_operator_tpu.train.trainer import cross_entropy_loss

        y = RMSNorm().apply({"params": hp["final_norm"]}, y)
        logits = (y.astype(cfg.dtype)
                  @ hp["lm_head"]["kernel"].astype(cfg.dtype))
        return cross_entropy_loss(logits, t_mb)

    if schedule == "gpipe":
        from tf_operator_tpu.parallel.pipeline import pipeline_lm_train_gpipe

        train = pipeline_lm_train_gpipe
    elif schedule == "1f1b":
        train = pipeline_lm_train_sharded
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    loss, sgrads, egrads, hgrads = train(
        stage_fn, loss_fn, embed_fn, stacked, embed_params, head_params,
        inputs, targets, mesh, num_microbatches, axis_name=axis_name)
    grads = {
        "embed_tokens": egrads["embed_tokens"],
        "blocks": sgrads if staged else merge_stage_params(sgrads),
        "final_norm": hgrads["final_norm"],
        "lm_head": hgrads["lm_head"],
    }
    return loss, grads


def init_llama_params(cfg: LlamaConfig, rng, sample_tokens: jax.Array):
    """Model-native init (same tree llama_pp_loss_and_grads consumes)."""
    return Llama(cfg).init(rng, sample_tokens)["params"]


# ---------------------------------------------------------------------------
# Pipeline trainer: 1F1B as a first-class training path
# ---------------------------------------------------------------------------

class LlamaPipelineTrainer:
    """Trainer-shaped wrapper over the 1F1B Llama step: sharded-from-
    birth init (blocks + their optimizer moments pp-sharded, embed/head
    replicated), and a jitted donating ``(state, tokens) -> (state,
    metrics)`` train step. Mirrors ``train.trainer.Trainer``'s
    init/make_train_step flow, with raw token arrays in place of batch
    dicts (the pipeline owns its own input split)."""

    def __init__(self, cfg: LlamaConfig, mesh, optimizer,
                 num_microbatches: int, axis_name: str = "pp",
                 schedule: str = "auto",
                 memory_budget_bytes: Optional[int] = None):
        """``schedule``: "gpipe", "1f1b", or "auto" (default). Auto
        compiles the GPipe step, reads XLA's memory analysis, and keeps
        GPipe iff its O(m) activation stash fits ``memory_budget_bytes``
        (default: the device's reported memory limit; unbounded when
        the platform reports none, e.g. CPU) — measured, GPipe is never
        slower when it fits (docs/benchmarks.md pipeline table), so
        1F1B is exactly the memory-ceiling escape hatch its O(pp) ring
        exists for. The resolved choice lands in
        ``self.resolved_schedule`` after make_train_step."""
        if schedule not in ("auto", "gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer
        self.num_microbatches = num_microbatches
        self.axis_name = axis_name
        self.pp = mesh.shape[axis_name]
        self.schedule = schedule
        self.memory_budget_bytes = memory_budget_bytes
        self.resolved_schedule: Optional[str] = (
            schedule if schedule != "auto" else None)

    def _placement(self, tree):
        """Path-based placement (the robust rule the GSPMD trainer uses
        for optimizer slots): any leaf whose path passes through
        'blocks' is a stage stack ([pp, L/pp, ...]) sharded over pp;
        scalars and everything else replicate. Adam mu/nu embed the
        param path as a suffix, so the same rule places them."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        stage = NamedSharding(self.mesh, P(self.axis_name))
        repl = NamedSharding(self.mesh, P())

        from tf_operator_tpu.train.trainer import path_names

        def place(path, leaf):
            if ("blocks" in path_names(path)
                    and getattr(leaf, "ndim", 0) > 0):
                return stage
            return repl

        return jax.tree_util.tree_map_with_path(place, tree)

    def _init_fn(self, sample_tokens):
        from tf_operator_tpu.train.trainer import TrainState

        def init_fn(rng):
            params = dict(Llama(self.cfg).init(
                rng, sample_tokens)["params"])
            params["blocks"] = split_stage_params(params["blocks"],
                                                  self.pp)
            opt_state = self.optimizer.init(params)
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=params, opt_state=opt_state)

        return init_fn

    def state_shardings(self, rng, sample_tokens):
        """Sharding tree from shapes alone (eval_shape — nothing
        materializes): the checkpoint-restore target builder, mirroring
        Trainer.state_shardings."""
        from tf_operator_tpu.train.trainer import TrainState

        abstract = jax.eval_shape(self._init_fn(sample_tokens), rng)
        return TrainState(
            step=jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec()),
            params=self._placement(abstract.params),
            opt_state=self._placement(abstract.opt_state))

    def init(self, rng, sample_tokens):
        """Returns (state, state_shardings); state is created sharded
        (jit with out_shardings — nothing materializes unsharded, the
        GSPMD trainer's init pattern)."""
        shardings = self.state_shardings(rng, sample_tokens)
        state = jax.jit(self._init_fn(sample_tokens),
                        out_shardings=shardings)(rng)
        return state, shardings

    def abstract_state(self, rng, sample_tokens, shardings=None):
        """Sharding-annotated abstract state without materializing
        anything — the checkpoint-restore target (mirrors
        Trainer.abstract_state)."""
        from tf_operator_tpu.train.checkpoint import (
            abstract_state_with_shardings,
        )

        if shardings is None:
            shardings = self.state_shardings(rng, sample_tokens)
        return abstract_state_with_shardings(
            self._init_fn(sample_tokens), shardings, rng)

    def _build_step(self, state_shardings, schedule: str):
        cfg, mesh, m = self.cfg, self.mesh, self.num_microbatches
        axis, opt = self.axis_name, self.optimizer

        @functools.partial(
            jax.jit,
            in_shardings=(state_shardings, None),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,))
        def step(state, tokens):
            loss, grads = llama_pp_loss_and_grads(cfg, state.params,
                                                  tokens, mesh, m,
                                                  axis_name=axis,
                                                  staged=True,
                                                  schedule=schedule)
            updates, opt_state = opt.update(grads, state.opt_state,
                                            state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(step=state.step + 1, params=params,
                                      opt_state=opt_state)
            return new_state, {"loss": loss}

        return step

    def _device_memory_budget(self) -> Optional[int]:
        if self.memory_budget_bytes is not None:
            return self.memory_budget_bytes
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            return int(limit) if limit else None
        except Exception:
            return None  # platform reports nothing (CPU): unbounded

    def _compile_probe(self, step, state_shardings, sample_tokens):
        """AOT-compile ``step`` for the probe shapes; returns (compiled
        executable or None, peak bytes or None). The executable is
        REUSED as the returned train step so the (usually minutes-long)
        pipeline compile is paid once, not once for the probe and again
        on the first real call."""
        from tf_operator_tpu.parallel.pipeline import compiled_peak_bytes

        try:
            abstract = self.abstract_state(jax.random.PRNGKey(0),
                                           sample_tokens,
                                           state_shardings)
            tok = jax.ShapeDtypeStruct(sample_tokens.shape,
                                       sample_tokens.dtype)
            compiled = step.lower(abstract, tok).compile()
        except Exception:
            return None, None
        return compiled, compiled_peak_bytes(compiled)

    def make_train_step(self, state_shardings, sample_tokens=None):
        """Compiled (state, tokens) -> (state, metrics) step.

        ``schedule="auto"`` needs ``sample_tokens`` (shape/dtype of the
        step's token batch) to size the GPipe memory probe. Without it
        — or when the probe fails — selection FAILS SAFE: GPipe only on
        platforms reporting no memory limit (CPU), 1F1B whenever a real
        budget exists but the footprint is unknown (a model that
        trained under 1F1B must never OOM from a silent default flip)."""
        from tf_operator_tpu.parallel.pipeline import select_schedule

        chosen = self.schedule
        if chosen == "auto":
            budget = self._device_memory_budget()
            compiled = None
            peak = None
            if sample_tokens is not None and budget is not None:
                gpipe_step = self._build_step(state_shardings, "gpipe")
                compiled, peak = self._compile_probe(
                    gpipe_step, state_shardings, sample_tokens)
            chosen = select_schedule(peak, budget)
            if chosen == "gpipe" and compiled is not None:
                self.resolved_schedule = chosen
                return compiled  # reuse the probe's executable
        self.resolved_schedule = chosen
        return self._build_step(state_shardings, chosen)
