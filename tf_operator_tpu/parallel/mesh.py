"""Device mesh construction.

The mesh is the foundation of every sharding decision: ICI axes come from
the slice topology, the DCN axis from the slice count ("How to Scale Your
Model" recipe: pick a mesh, annotate shardings, let XLA insert
collectives). Axis convention, outermost first:

    ("dcn", "dp", "fsdp", "pp", "sp", "tp", "ep")

- dcn: across slices (data parallel over DCN; multislice).
- dp: pure data parallel (replicated params).
- fsdp: data parallel with sharded params/optimizer (ZeRO-3).
- pp: pipeline stages.
- sp: sequence/context parallel (ring attention rides this axis).
- tp: tensor parallel (megatron-style head/ffn sharding).
- ep: expert parallel (MoE); typically aliased onto tp or its own axis.

Axes of size 1 are kept in the mesh — PartitionSpecs can then mention
every logical axis unconditionally and XLA drops the no-op collectives.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dcn", "dp", "fsdp", "pp", "sp", "tp", "ep")


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` even when a site-installed PJRT plugin
    pins the backend at interpreter startup.

    Some TPU plugin sitecustomize hooks register themselves and claim
    the default backend regardless of the ``JAX_PLATFORMS`` env var.
    Payloads that are told ``JAX_PLATFORMS=cpu`` (hermetic e2e, CI)
    call this before the first device query; jax.config wins over the
    plugin's pin. No-op when the env var is unset; a silent no-op if
    the backend is already initialized.
    """
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)

# Ambient mesh: models reach it for nested shard_map regions (ring
# attention, MoE dispatch) without threading a Mesh through module attrs.
_MESH_STACK: list = []


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    _MESH_STACK.append(mesh)
    try:
        yield mesh
    finally:
        _MESH_STACK.pop()


def active_mesh() -> Optional[Mesh]:
    return _MESH_STACK[-1] if _MESH_STACK else None


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 on at most one axis means 'absorb remaining devices'."""

    dcn: int = 1
    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh over ``devices`` (default: all local devices).

    Device order follows jax.devices(), which enumerates ICI-adjacent
    devices contiguously — putting the *innermost* (rightmost) mesh axes on
    nearest neighbors. Bandwidth-hungry axes (tp/ep/sp) are rightmost in
    AXIS_ORDER for exactly this reason; dcn is outermost so slices map to
    the slowest links.
    """
    config = config or MeshConfig()
    if devices is None:
        apply_platform_env()
        devices = jax.devices()
    devices = np.asarray(devices)
    sizes = config.resolve(devices.size)
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    return Mesh(devices.reshape(shape), AXIS_ORDER)


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Mesh axes a [batch, ...] input's leading dim shards over."""
    return tuple(a for a in ("dcn", "dp", "fsdp") if a in mesh.axis_names)


def data_parallel_size(mesh: Mesh) -> int:
    return math.prod(mesh.shape[a] for a in data_axes(mesh))
