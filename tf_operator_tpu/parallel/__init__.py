"""Parallelism: mesh construction, sharding rules, collectives, ring
attention, pipeline parallelism.

No reference analog — the reference's "parallelism" is process-topology
orchestration (SURVEY §2.3); the actual distribution lived in user
containers. Here it is first-class: GSPMD/pjit sharding (DP/FSDP/TP/EP),
shard_map+ppermute for sequence/context (ring attention) and pipeline
parallelism, over meshes derived from the slice topology (ICI axes) and
slice count (DCN axis).
"""

from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
from tf_operator_tpu.parallel.sharding import (  # noqa: F401
    LLAMA_RULES,
    MOE_RULES,
    logical_sharding,
    shard_pytree,
)
