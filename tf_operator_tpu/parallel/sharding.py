"""Logical-axis sharding rules (t5x/maxtext-style).

Parameters are annotated with *logical* axis names ("embed", "heads",
"mlp", "expert", ...); rule tables map logical axes to mesh axes. This
keeps models mesh-agnostic: the same Llama definition runs pure-DP,
FSDP, TP, or any combination by swapping the rule table.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]
Rules = Dict[str, MeshAxes]

# Dense transformer (Llama/BERT family), megatron TP + FSDP:
# - embed dim sharded over fsdp (ZeRO-3 gather on use)
# - attention heads + ffn hidden sharded over tp
# - vocab sharded over tp (output projection all-gather)
LLAMA_RULES: Rules = {
    "batch": ("dcn", "dp", "fsdp"),
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "seq": "sp",
    "kv_seq": None,
    "layers": None,
    "norm": None,
}

# MoE (Mixtral family): experts sharded over ep, expert-internal mlp over tp.
# "capacity" names the slot dim of the gather-dispatch permutation
# intermediates (models/mixtral.py _gather_route): the capacity-packed
# [expert, capacity, embed] buffers shard their expert dim over ep —
# so the pack/unpack gather+scatter lowers to all-to-alls exactly like
# the one-hot dispatch einsums — while slots stay local to the expert.
MOE_RULES: Rules = {
    **LLAMA_RULES,
    "expert": "ep",
    "capacity": None,
}

# Conv/vision nets (ResNet): pure data parallel; params replicated.
CNN_RULES: Rules = {
    "batch": ("dcn", "dp", "fsdp"),
}


def spec_for(logical_axes: Sequence[Optional[str]], rules: Rules) -> P:
    """Translate logical axis names to a PartitionSpec via the rule table."""
    return P(*(rules.get(a) if a is not None else None
               for a in logical_axes))


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                     rules: Rules) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def shard_pytree(tree, mesh: Mesh, axes_tree, rules: Rules):
    """Place a pytree on the mesh: ``axes_tree`` mirrors ``tree`` with
    logical-axis tuples (None = replicate)."""

    def place(x, axes):
        if axes is None:
            sharding = NamedSharding(mesh, P())
        else:
            sharding = logical_sharding(mesh, axes, rules)
        return jax.device_put(x, sharding)

    return jax.tree.map(place, tree, axes_tree,
                        is_leaf=lambda x: x is None)


def batch_sharding(mesh: Mesh, rules: Rules = LLAMA_RULES) -> NamedSharding:
    """Sharding for [batch, ...] host data."""
    return NamedSharding(mesh, P(rules.get("batch")))


def constrain(x, logical_axes: Sequence[Optional[str]], rules: Rules):
    """``with_sharding_constraint`` via logical axes against the ambient
    mesh (``mesh.use_mesh``); identity when no mesh is active, so model
    code can pin activation intermediates (e.g. the MoE gather-dispatch
    expert buffers) unconditionally."""
    from tf_operator_tpu.parallel.mesh import active_mesh

    mesh = active_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules))
