"""Pipeline parallelism over the ``pp`` mesh axis.

The reference has no pipeline parallelism anywhere (SURVEY §2.3 —
TP/PP/SP/EP absent); in this framework it is a harness feature, built
the TPU-idiomatic way: explicit microbatch schedules inside
``shard_map``, with activations handed to the next stage by
``ppermute`` (ICI neighbor transfers), not a port of any
send/recv-thread design.

Two schedules:

- **GPipe** (``pipeline_apply``/``pipeline_sharded``): forward-only
  scan; the backward schedule falls out of autodiff. Simple, but scan
  autodiff stashes one activation per step — O(m) microbatch residuals
  per rank — and the default all-gather of outputs broadcasts the full
  activation tensor around the ring.
- **1F1B** (``pipeline_train_sharded``): a fused forward+backward
  schedule with a manual VJP. Each tick runs one (masked) forward and
  one (masked) recompute-backward; stage s starts microbatch j's
  forward at tick s+j and its backward at tick 2(pp-1)-s+j, so a
  residual needs to live only 2(pp-1-s) ticks — a ring buffer of
  depth 2·pp bounds activation memory at O(pp) microbatches per rank
  regardless of m (the 1F1B memory property). Only the scalar loss
  crosses stages at the end (psum of one number); the full output
  tensor is never broadcast. Backward recomputes the stage forward
  from the stashed input (remat-style), so per-microbatch compute is
  1 fwd + ~2 bwd units, the same as GPipe-with-remat.

How it maps to hardware:
- each pp rank holds one *stage* (a contiguous chunk of layers whose
  params carry a leading stage axis sharded over ``pp``);
- one scan tick = masked stage compute(s), then ppermute: activations
  ring-forward, cotangents ring-backward; XLA overlaps the permutes
  with the next tick's compute (async collectives);
- bubble: GPipe runs m+pp-1 forward ticks (fraction (pp-1)/(m+pp-1));
  1F1B runs m+2(pp-1) fused ticks. Amortize with more microbatches —
  measured curves in benchmarks/bench_pipeline.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.compat import shard_map

from tf_operator_tpu.parallel.mesh import data_axes

# stage_fn(stage_params, x) -> y, applied by every pp rank to its own
# stage params. x/y must have identical shape/dtype (residual-stream
# style), which is what makes the ring handoff well-typed.
StageFn = Callable[[Any, jax.Array], jax.Array]


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] -> [m, B/m, ...] (leading microbatch axis)."""
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} not divisible by {num_microbatches} microbatches")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def merge_microbatches(x: jax.Array) -> jax.Array:
    """[m, B/m, ...] -> [B, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def pipeline_apply(stage_fn: StageFn, stage_params: Any,
                   microbatches: jax.Array,
                   axis_name: str = "pp",
                   gather_output: bool = True) -> jax.Array:
    """GPipe schedule; call inside shard_map (stage_params = this rank's
    stage, microbatches [m, mb, ...] identical on every pp rank).

    With ``gather_output`` the [m, mb, ...] outputs are replicated to
    every pp rank (a ring-wide psum of the full tensor — convenient but
    expensive); without it they are valid on the LAST stage only (zeros
    elsewhere), for callers that reduce to a scalar there.
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        state, outputs = carry
        # Stage 0 feeds a fresh microbatch; later stages consume the
        # activation ppermuted in by the previous step.
        x_t = lax.dynamic_index_in_dim(microbatches, t % m, axis=0,
                                       keepdims=False)
        inp = jnp.where(stage == 0, x_t, state)
        y = stage_fn(stage_params, inp)
        # The last stage finishes microbatch t-(n_stages-1) at step t.
        out_idx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        slot = jnp.maximum(out_idx, 0)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y,
                      lax.dynamic_index_in_dim(outputs, slot, axis=0,
                                               keepdims=False)),
            slot, axis=0)
        state = lax.ppermute(y, axis_name, fwd_ring)
        return (state, updated), None

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(step, (state0, out0),
                               jnp.arange(m + n_stages - 1))
    # Outputs are only valid on the last stage.
    outputs = jnp.where(stage == n_stages - 1, outputs,
                        jnp.zeros_like(outputs))
    if gather_output:
        # Replicate across the ring so downstream (loss) code is
        # rank-agnostic — full-tensor traffic; prefer the 1F1B trainer
        # (scalar-only reduction) for training steps.
        outputs = lax.psum(outputs, axis_name)
    return outputs


def pipeline_sharded(stage_fn: StageFn, stacked_params: Any, x: jax.Array,
                     mesh: Mesh, num_microbatches: int,
                     axis_name: str = "pp") -> jax.Array:
    """Global-view pipeline: ``stacked_params`` leaves carry a leading
    [pp] stage axis (sharded over the pp mesh axis); ``x`` is the global
    [B, ...] activation batch (B sharded over the data axes).

    Splits x into microbatches, runs the GPipe schedule under shard_map,
    and merges back to [B, ...].
    """
    batch_axes = data_axes(mesh)
    pspec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axes)   # [m, mb, ...]: mb sharded over data axes

    def inner(params, mb):
        # Inside shard_map the leading stage axis is size 1 on each rank.
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        return pipeline_apply(stage_fn, local, mb, axis_name=axis_name)

    fn = shard_map(inner, mesh=mesh, in_specs=(pspec, xspec),
                       out_specs=xspec, check_vma=False)
    return merge_microbatches(fn(stacked_params,
                                 split_microbatches(x, num_microbatches)))


def stack_stage_params(per_stage_params: list) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading [pp]
    axis on every leaf (the layout pipeline_sharded expects)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


# ---------------------------------------------------------------------------
# 1F1B training schedule (manual VJP, O(pp) activation memory)
# ---------------------------------------------------------------------------

# loss_fn(y, targets) -> scalar mean loss for one microbatch.
LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def pipeline_train_1f1b(stage_fn: StageFn, loss_fn: LossFn,
                        stage_params: Any, microbatches: jax.Array,
                        targets: jax.Array, n_stages: int,
                        axis_name: str = "pp",
                        head_params: Any = None,
                        return_input_grads: bool = False):
    """Fused forward/backward pipeline; call inside shard_map.

    Schedule (tick = one scan step; both slots run masked every tick):
      forward of microbatch j at stage s  -> tick  s + j
      backward of microbatch j at stage s -> tick  2(pp-1) - s + j
    so the last stage backwards j in the same tick it forwards it, the
    cotangent rides the reverse ring one stage per tick, and stage s
    holds at most 2(pp-1-s) live residuals — the ring buffer of depth
    2·pp makes activation memory independent of the microbatch count.

    Returns (mean loss, grads for THIS rank's stage). Only the scalar
    loss is psum'd; gradients stay stage-sharded.

    Full-model composition (an LM, not just a residual trunk):

    - ``head_params``: extra differentiable params for the loss head
      (e.g. the unembedding); ``loss_fn(y, targets, head_params)`` runs
      on the LAST stage and their gradients come back psum-replicated.
    - ``return_input_grads``: also return d(loss)/d(microbatches) —
      valid on stage 0 (zeros elsewhere) — so the caller can close the
      chain through its own embedding with ``jax.vjp``.

    With either option the return is (loss, stage_grads, aux) where
    aux = {"head_grads": ..., "input_grads": ...}.
    """
    pp = n_stages
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    ring_depth = 2 * pp
    ticks = m + 2 * (pp - 1)
    fwd_ring = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_ring = [(i, (i - 1) % pp) for i in range(pp)]
    with_head = head_params is not None
    hp0 = head_params if with_head else {}

    def mb_at(arr, j):
        return lax.dynamic_index_in_dim(arr, jnp.clip(j, 0, m - 1),
                                        axis=0, keepdims=False)

    def head_loss(y, t_mb, hp):
        return loss_fn(y, t_mb, hp) if with_head else loss_fn(y, t_mb)

    grads0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    hgrads0 = jax.tree_util.tree_map(jnp.zeros_like, hp0)
    ring0 = jnp.zeros((ring_depth,) + microbatches.shape[1:],
                      microbatches.dtype)
    # Only materialized when requested: an O(m) fp32 carry would
    # silently void the O(pp) activation-memory property otherwise.
    dmb0 = (jnp.zeros(microbatches.shape, jnp.float32)
            if return_input_grads else jnp.zeros((0,), jnp.float32))
    state0 = jnp.zeros_like(microbatches[0])

    def step(carry, t):
        fwd_state, bwd_state, ring, grads, hgrads, dmb, loss_sum = carry

        # -- forward slot: microbatch fj enters this stage ---------------
        fj = t - stage
        fwd_valid = jnp.logical_and(fj >= 0, fj < m)
        x_in = jnp.where(stage == 0, mb_at(microbatches, fj), fwd_state)
        y = stage_fn(stage_params, x_in)
        # Stash the stage INPUT (the backward recomputes the forward
        # from it, remat-style); masked write keeps stale slots intact.
        slot = jnp.clip(fj, 0, m - 1) % ring_depth
        old = lax.dynamic_index_in_dim(ring, slot, axis=0, keepdims=False)
        ring = lax.dynamic_update_index_in_dim(
            ring, jnp.where(fwd_valid, x_in, old), slot, axis=0)

        # -- backward slot: microbatch bj leaves this stage --------------
        bj = t - 2 * (pp - 1) + stage
        bwd_valid = jnp.logical_and(bj >= 0, bj < m)
        bslot = jnp.clip(bj, 0, m - 1) % ring_depth
        x_res = lax.dynamic_index_in_dim(ring, bslot, axis=0,
                                         keepdims=False)
        y_re, vjp_fn = jax.vjp(stage_fn, stage_params, x_res)
        t_mb = mb_at(targets, bj)

        # The loss head only matters on the LAST stage; a cond (legal
        # here: no collectives inside, scalar per-device predicate)
        # keeps the head forward+backward — for an LM, the full-vocab
        # matmul — off the other pp-1 stages entirely.
        def run_head(args):
            y_h, t_h = args
            lv, (dyl, dh) = jax.value_and_grad(
                head_loss, argnums=(0, 2))(y_h, t_h, hp0)
            return lv.astype(jnp.float32), dyl, dh

        def skip_head(args):
            y_h, _ = args
            return (jnp.zeros((), jnp.float32), jnp.zeros_like(y_h),
                    jax.tree_util.tree_map(jnp.zeros_like, hp0))

        loss_val, dy_last, dhead = lax.cond(
            stage == pp - 1, run_head, skip_head, (y_re, t_mb))
        dy = jnp.where(stage == pp - 1, dy_last, bwd_state)
        dparams, dx = vjp_fn(dy)
        # Select, don't multiply-by-zero: bubble ticks run the backward
        # on garbage residuals, and 0·NaN would poison every real
        # gradient (e.g. log-losses on zeroed ring slots).
        grads = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(bwd_valid, d, jnp.zeros_like(d)),
            grads, dparams)
        head_valid = jnp.logical_and(bwd_valid, stage == pp - 1)
        hgrads = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(head_valid, d, jnp.zeros_like(d)),
            hgrads, dhead)
        if return_input_grads:
            # Stage 0's dx is d(loss)/d(microbatch bj): stash for the
            # caller's embedding vjp.
            dmb = lax.dynamic_update_index_in_dim(
                dmb, jnp.where(jnp.logical_and(bwd_valid, stage == 0),
                               dx.astype(jnp.float32), mb_at(dmb, bj)),
                jnp.clip(bj, 0, m - 1), axis=0)
        loss_sum = loss_sum + jnp.where(head_valid, loss_val, 0.0)

        # -- ring handoffs (XLA overlaps with next tick's compute) -------
        fwd_state = lax.ppermute(y, axis_name, fwd_ring)
        bwd_state = lax.ppermute(dx, axis_name, bwd_ring)
        return (fwd_state, bwd_state, ring, grads, hgrads, dmb,
                loss_sum), None

    carry0 = (state0, jnp.zeros_like(state0), ring0, grads0, hgrads0,
              dmb0, jnp.zeros((), jnp.float32))
    (_, _, _, grads, hgrads, dmb, loss_sum), _ = lax.scan(
        step, carry0, jnp.arange(ticks))
    # Mean over microbatches; scalars/head-grads are the only
    # cross-stage reductions (head grads live on the last stage only).
    loss = lax.psum(loss_sum / m, axis_name)
    grads = jax.tree_util.tree_map(lambda g: g / m, grads)
    if not with_head and not return_input_grads:
        return loss, grads
    hgrads = jax.tree_util.tree_map(
        lambda g: lax.psum(g / m, axis_name), hgrads)
    aux = {"head_grads": hgrads if with_head else None,
           "input_grads": dmb / m if return_input_grads else None}
    return loss, grads, aux


def pipeline_train_sharded(stage_fn: StageFn, loss_fn: LossFn,
                           stacked_params: Any, x: jax.Array,
                           targets: jax.Array, mesh: Mesh,
                           num_microbatches: int,
                           axis_name: str = "pp"):
    """Global-view 1F1B training step: returns (mean loss, grads with
    the leading [pp] stage axis, sharded like ``stacked_params``).

    Compose with an optimizer for a full PP training step; the loss is
    replicated, gradients never leave their stage.
    """
    n_stages = mesh.shape[axis_name]
    batch_axes = data_axes(mesh)
    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axes)

    def inner(params, mb, tgt):
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        loss, grads = pipeline_train_1f1b(stage_fn, loss_fn, local, mb,
                                          tgt, n_stages,
                                          axis_name=axis_name)
        # Average grads over the data axes (each dp shard saw its own
        # microbatches), mirroring the usual DP all-reduce.
        if batch_axes:
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, batch_axes), grads)
            loss = lax.pmean(loss, batch_axes)
        # Re-attach the stage axis for the global [pp, ...] layout.
        grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        return loss, grads

    fn = shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, xspec, xspec),
        out_specs=(P(), pspec),
        check_vma=False)
    return fn(stacked_params, split_microbatches(x, num_microbatches),
              split_microbatches(targets, num_microbatches))


def pipeline_lm_train_gpipe(stage_fn: StageFn, loss_fn, embed_fn,
                            stacked_params: Any, embed_params: Any,
                            head_params: Any, inputs: jax.Array,
                            targets: jax.Array, mesh: Mesh,
                            num_microbatches: int,
                            axis_name: str = "pp"):
    """GPipe counterpart of :func:`pipeline_lm_train_sharded`: forward
    through the GPipe schedule, backward by autodiff. Fewer ticks
    (m + pp - 1 vs m + 2(pp-1)) and no recompute, at the cost of an
    O(m)-microbatch activation stash per stage — the faster schedule
    whenever that stash fits in memory (measured: docs/benchmarks.md
    pipeline table; 1F1B never beat it on any config that fit). Same
    signature and return contract as the 1F1B variant, so callers
    switch schedules without touching model code."""
    def total_loss(sp, ep, hp):
        h = embed_fn(ep, inputs)  # embedding lookup, any leading dims
        y = pipeline_sharded(stage_fn, sp, h, mesh, num_microbatches,
                             axis_name=axis_name)
        # Mean loss over the full batch == mean over equal microbatches,
        # so the scalar matches the 1F1B schedule's exactly.
        return loss_fn(y, targets, hp)

    loss, (sgrads, egrads, hgrads) = jax.value_and_grad(
        total_loss, argnums=(0, 1, 2))(stacked_params, embed_params,
                                       head_params)
    return loss, sgrads, egrads, hgrads


# Activation-memory safety margin for schedule selection: compiled peak
# estimates undercount fragmentation/runtime buffers.
_SCHEDULE_MEM_SAFETY = 0.9


def select_schedule(gpipe_peak_bytes: Optional[int],
                    budget_bytes: Optional[int]) -> str:
    """Pick the pipeline schedule from the memory trade-off.

    Measured result (docs/benchmarks.md pipeline table, r2-r4): GPipe
    is faster than 1F1B on EVERY config where its O(m) activation stash
    fits — 1F1B pays remat plus pp-1 extra ticks of schedule overhead;
    its win is the O(pp) memory ceiling. So: GPipe when it fits, 1F1B
    when it would not.

    Fail SAFE, not open: with a known memory budget but an unknown
    GPipe peak (probe unavailable/failed), pick 1F1B — the bounded-
    memory schedule is the one that cannot OOM a model that fit
    before. Only an unbounded budget (platform reports no limit)
    defaults to GPipe.
    """
    if budget_bytes is None:
        return "gpipe"
    if gpipe_peak_bytes is None or gpipe_peak_bytes < 0:
        return "1f1b"  # budget known, footprint unknown: don't gamble
    if gpipe_peak_bytes <= budget_bytes * _SCHEDULE_MEM_SAFETY:
        return "gpipe"
    return "1f1b"


def compiled_peak_bytes(compiled) -> Optional[int]:
    """XLA's working-set estimate for a compiled computation: temp (the
    activation stash lives here) plus non-aliased argument bytes. The
    ONE formula both the trainer's auto probe and bench_pipeline report
    — they must not diverge, or the bench's auto_choice columns would
    stop describing what schedule="auto" actually does."""
    try:
        ma = compiled.memory_analysis()
        return int(ma.temp_size_in_bytes + ma.argument_size_in_bytes
                   - ma.alias_size_in_bytes)
    except Exception:
        return None


def pipeline_lm_train_sharded(stage_fn: StageFn, loss_fn, embed_fn,
                              stacked_params: Any, embed_params: Any,
                              head_params: Any, inputs: jax.Array,
                              targets: jax.Array, mesh: Mesh,
                              num_microbatches: int,
                              axis_name: str = "pp"):
    """Full-model 1F1B training step: embedding -> pp-sharded stage
    trunk -> loss head, with exact gradients for all three param groups.

    - ``embed_fn(embed_params, inputs_mb)`` maps raw microbatched inputs
      [m, mb, ...] to trunk activations (computed replicated on every pp
      rank — one cheap gather vs a dedicated embedding stage);
    - ``loss_fn(y, targets_mb, head_params)`` runs on the last stage;
    - the trunk runs the fused 1F1B schedule; stage-0 input cotangents
      close the chain through the embedding via ``jax.vjp``.

    Returns (loss, stage_grads [pp-sharded], embed_grads, head_grads)
    with embed/head grads replicated.
    """
    n_stages = mesh.shape[axis_name]
    batch_axes = data_axes(mesh)
    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axes)

    def inner(params, eparams, hparams, inp, tgt):
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        x_mb, embed_vjp = jax.vjp(lambda ep: embed_fn(ep, inp), eparams)
        loss, sgrads, aux = pipeline_train_1f1b(
            stage_fn, loss_fn, local, x_mb, tgt, n_stages,
            axis_name=axis_name, head_params=hparams,
            return_input_grads=True)
        # input_grads are valid on stage 0 only; replicate around the
        # ring, then pull the embedding gradient out of its vjp.
        dmb = lax.psum(aux["input_grads"], axis_name)
        (egrads,) = embed_vjp(dmb.astype(x_mb.dtype))
        hgrads = aux["head_grads"]
        if batch_axes:
            mean = functools.partial(lax.pmean, axis_name=batch_axes)
            loss = mean(loss)
            sgrads = jax.tree_util.tree_map(mean, sgrads)
            egrads = jax.tree_util.tree_map(mean, egrads)
            hgrads = jax.tree_util.tree_map(mean, hgrads)
        sgrads = jax.tree_util.tree_map(lambda g: g[None], sgrads)
        return loss, sgrads, egrads, hgrads

    espec = jax.tree_util.tree_map(lambda _: P(), embed_params)
    hspec = jax.tree_util.tree_map(lambda _: P(), head_params)
    fn = shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, espec, hspec, xspec, xspec),
        out_specs=(P(), pspec, espec, hspec), check_vma=False)
    return fn(stacked_params, embed_params, head_params,
              split_microbatches(inputs, num_microbatches),
              split_microbatches(targets, num_microbatches))
