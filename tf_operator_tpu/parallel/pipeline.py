"""Pipeline parallelism over the ``pp`` mesh axis.

The reference has no pipeline parallelism anywhere (SURVEY §2.3 —
TP/PP/SP/EP absent); in this framework it is a harness feature, built
the TPU-idiomatic way: an explicit GPipe-style microbatch schedule
inside ``shard_map``, with activations handed to the next stage by
``ppermute`` (ICI neighbor transfers), not a port of any
send/recv-thread design.

How it maps to hardware:
- each pp rank holds one *stage* (a contiguous chunk of layers whose
  params carry a leading stage axis sharded over ``pp``);
- one scan step = every stage computes its microbatch then ppermutes
  the activation ring-forward; XLA overlaps the permute with the next
  step's compute (async collective);
- the schedule runs ``num_microbatches + pp - 1`` steps; the ``pp - 1``
  bubble steps compute garbage that is masked out of the output. Bubble
  fraction = (pp-1)/(m+pp-1): amortize with more microbatches;
- everything is ``lax.scan`` + ``ppermute`` — differentiable, so the
  backward pipeline schedule falls out of autodiff for free.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.parallel.mesh import data_axes

# stage_fn(stage_params, x) -> y, applied by every pp rank to its own
# stage params. x/y must have identical shape/dtype (residual-stream
# style), which is what makes the ring handoff well-typed.
StageFn = Callable[[Any, jax.Array], jax.Array]


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] -> [m, B/m, ...] (leading microbatch axis)."""
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} not divisible by {num_microbatches} microbatches")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def merge_microbatches(x: jax.Array) -> jax.Array:
    """[m, B/m, ...] -> [B, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def pipeline_apply(stage_fn: StageFn, stage_params: Any,
                   microbatches: jax.Array,
                   axis_name: str = "pp") -> jax.Array:
    """GPipe schedule; call inside shard_map (stage_params = this rank's
    stage, microbatches [m, mb, ...] identical on every pp rank).

    Returns the full [m, mb, ...] outputs on every pp rank.
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        state, outputs = carry
        # Stage 0 feeds a fresh microbatch; later stages consume the
        # activation ppermuted in by the previous step.
        x_t = lax.dynamic_index_in_dim(microbatches, t % m, axis=0,
                                       keepdims=False)
        inp = jnp.where(stage == 0, x_t, state)
        y = stage_fn(stage_params, inp)
        # The last stage finishes microbatch t-(n_stages-1) at step t.
        out_idx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        slot = jnp.maximum(out_idx, 0)
        updated = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, y,
                      lax.dynamic_index_in_dim(outputs, slot, axis=0,
                                               keepdims=False)),
            slot, axis=0)
        state = lax.ppermute(y, axis_name, fwd_ring)
        return (state, updated), None

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(step, (state0, out0),
                               jnp.arange(m + n_stages - 1))
    # Outputs are only valid on the last stage; replicate them across the
    # ring so downstream (loss) code is rank-agnostic.
    outputs = jnp.where(stage == n_stages - 1, outputs,
                        jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def pipeline_sharded(stage_fn: StageFn, stacked_params: Any, x: jax.Array,
                     mesh: Mesh, num_microbatches: int,
                     axis_name: str = "pp") -> jax.Array:
    """Global-view pipeline: ``stacked_params`` leaves carry a leading
    [pp] stage axis (sharded over the pp mesh axis); ``x`` is the global
    [B, ...] activation batch (B sharded over the data axes).

    Splits x into microbatches, runs the GPipe schedule under shard_map,
    and merges back to [B, ...].
    """
    batch_axes = data_axes(mesh)
    pspec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    xspec = P(None, batch_axes)   # [m, mb, ...]: mb sharded over data axes

    def inner(params, mb):
        # Inside shard_map the leading stage axis is size 1 on each rank.
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        return pipeline_apply(stage_fn, local, mb, axis_name=axis_name)

    fn = jax.shard_map(inner, mesh=mesh, in_specs=(pspec, xspec),
                       out_specs=xspec, check_vma=False)
    return merge_microbatches(fn(stacked_params,
                                 split_microbatches(x, num_microbatches)))


def stack_stage_params(per_stage_params: list) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading [pp]
    axis on every leaf (the layout pipeline_sharded expects)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
