"""Shared test fixtures: TPUJob/pod/endpoint builders.

Reference: pkg/common/util/v1/testutil/ (tfjob.go:27-247 builders for every
topology/policy combo; pod.go:38-95 phase-stamped fake pods; service.go).
Shipped in-package, like the reference, so SDK/e2e tests can reuse it.
"""

from __future__ import annotations

import datetime as _dt
import itertools
import uuid
from typing import Dict, List, Optional

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.engine import JobPlugin
from tf_operator_tpu.api.types import (
    Container,
    Endpoint,
    EndpointSpec,
    ContainerStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    PodTemplateSpec,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
    TPUSliceSpec,
    gen_general_name,
)

TEST_JOB_NAME = "test-tpujob"
TEST_NAMESPACE = "default"
_seq = itertools.count()


def now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def new_pod_template(command: Optional[List[str]] = None) -> PodTemplateSpec:
    return PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name=constants.DEFAULT_CONTAINER_NAME,
                    command=command or ["python", "-c", "pass"],
                )
            ]
        )
    )


def new_replica_spec(replicas: int,
                     restart_policy: str = "",
                     command: Optional[List[str]] = None) -> ReplicaSpec:
    return ReplicaSpec(replicas=replicas, template=new_pod_template(command),
                       restart_policy=restart_policy)


def new_tpujob(worker: int = 0,
               ps: int = 0,
               chief: int = 0,
               evaluator: int = 0,
               master: int = 0,
               actor: int = 0,
               name: str = TEST_JOB_NAME,
               namespace: str = TEST_NAMESPACE,
               command: Optional[List[str]] = None,
               accelerator: str = "") -> TPUJob:
    """Builder covering the reference's NewTFJob* matrix (testutil/tfjob.go).

    ``actor`` adds a bare actor replica spec (docs/rl.md); attach a
    RolePolicy to it yourself — the builder stamps none so role-policy
    defaults stay byte-identical to a policy-free job."""
    specs: Dict[str, ReplicaSpec] = {}
    for rtype, n in ((ReplicaType.WORKER, worker), (ReplicaType.PS, ps),
                     (ReplicaType.CHIEF, chief), (ReplicaType.EVALUATOR, evaluator),
                     (ReplicaType.MASTER, master), (ReplicaType.ACTOR, actor)):
        if n > 0:
            specs[rtype] = new_replica_spec(n, command=command)
    job = TPUJob(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=str(uuid.uuid4()),
            creation_timestamp=now(),
        ),
        spec=TPUJobSpec(replica_specs=specs,
                        slice=TPUSliceSpec(accelerator=accelerator)),
    )
    return job


def owner_ref(job: TPUJob) -> OwnerReference:
    return OwnerReference(api_version=job.api_version, kind=job.kind,
                          name=job.metadata.name, uid=job.metadata.uid,
                          controller=True)


def replica_labels(job: TPUJob, rtype: str, index: int) -> Dict[str, str]:
    return {
        constants.LABEL_GROUP_NAME: constants.GROUP,
        constants.LABEL_JOB_NAME: job.metadata.name,
        constants.LABEL_REPLICA_TYPE: rtype.lower(),
        constants.LABEL_REPLICA_INDEX: str(index),
    }


def new_pod(job: TPUJob, rtype: str, index: int,
            phase: str = PodPhase.PENDING,
            exit_code: Optional[int] = None,
            owned: bool = True) -> Pod:
    """Phase-stamped fake pod (reference testutil/pod.go:38-95)."""
    meta = ObjectMeta(
        name=gen_general_name(job.metadata.name, rtype, index),
        namespace=job.metadata.namespace,
        uid=str(uuid.uuid4()),
        labels=replica_labels(job, rtype, index),
        creation_timestamp=now(),
        resource_version=next(_seq),
    )
    if owned:
        meta.owner_references = [owner_ref(job)]
    pod = Pod(metadata=meta,
              spec=job.spec.replica_specs[rtype].template.spec.deepcopy()
              if rtype in job.spec.replica_specs else PodSpec(
                  containers=[Container()]),
              status=PodStatus(phase=phase))
    if phase in (PodPhase.RUNNING, PodPhase.SUCCEEDED, PodPhase.FAILED):
        pod.status.start_time = now()
    if exit_code is not None or phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
        code = exit_code if exit_code is not None else (
            0 if phase == PodPhase.SUCCEEDED else 1)
        pod.status.container_statuses = [ContainerStatus(
            name=constants.DEFAULT_CONTAINER_NAME, state="Terminated",
            exit_code=code)]
    return pod


def new_pod_list(job: TPUJob, rtype: str, count: int,
                 phase: str = PodPhase.PENDING, start: int = 0) -> List[Pod]:
    return [new_pod(job, rtype, i, phase=phase)
            for i in range(start, start + count)]


def set_pod_statuses(pods: List[Pod], job: TPUJob, rtype: str,
                     pending: int = 0, active: int = 0, succeeded: int = 0,
                     failed: int = 0) -> None:
    """Bulk phase stamping (reference testutil/pod.go:67 SetPodsStatuses):
    appends pods of the given phases with consecutive indices."""
    idx = len([p for p in pods
               if p.metadata.labels.get(constants.LABEL_REPLICA_TYPE) == rtype.lower()])
    for phase, n in ((PodPhase.PENDING, pending), (PodPhase.RUNNING, active),
                     (PodPhase.SUCCEEDED, succeeded), (PodPhase.FAILED, failed)):
        for _ in range(n):
            pods.append(new_pod(job, rtype, idx, phase=phase))
            idx += 1


def new_endpoint(job: TPUJob, rtype: str, index: int) -> Endpoint:
    return Endpoint(
        metadata=ObjectMeta(
            name=gen_general_name(job.metadata.name, rtype, index),
            namespace=job.metadata.namespace,
            uid=str(uuid.uuid4()),
            labels=replica_labels(job, rtype, index),
            owner_references=[owner_ref(job)],
        ),
        spec=EndpointSpec(selector=replica_labels(job, rtype, index),
                          ports={constants.DEFAULT_PORT_NAME: constants.DEFAULT_PORT}),
    )


class StubPlugin(JobPlugin):
    """In-memory JobPlugin for engine tests: observed state is whatever the
    test stuffs into .pods/.endpoints; API writes are recorded. This is the
    reference's fake-clientset + AlwaysReady informer seam
    (testutil/util.go:46-95) collapsed into one object."""

    def __init__(self, pods=None, endpoints=None):
        self.pods = list(pods or [])
        self.endpoints = list(endpoints or [])
        self.status_writes = []
        self.deleted_jobs = []
        self.cluster_spec_calls = []
        self.workqueue = None  # optionally set by tests

    def get_pods_for_job(self, job):
        return list(self.pods)

    def get_endpoints_for_job(self, job):
        return list(self.endpoints)

    def delete_job(self, job):
        self.deleted_jobs.append(job.metadata.name)

    def update_job_status(self, job, replica_specs, pods=None):
        from tf_operator_tpu.controller import status as status_mod

        pods = self.pods if pods is None else pods
        w0 = status_mod.is_worker0_completed(
            job, replica_specs, pods, self.get_default_container_name())
        status_mod.update_job_status(job, replica_specs, w0,
                                     workqueue=self.workqueue)

    def update_job_status_in_api(self, job):
        self.status_writes.append(job.status.deepcopy())

    def set_cluster_spec(self, job, pod, rtype, index):
        self.cluster_spec_calls.append((rtype, index))
        container = pod.spec.container(self.get_default_container_name())
        if container is not None:
            container.env["TPU_WORKER_ID"] = str(index)


def get_condition(job: TPUJob, cond_type: str):
    for c in job.status.conditions:
        if c.type == cond_type:
            return c
    return None


def check_condition(job: TPUJob, cond_type: str, reason: str = "") -> bool:
    """Reference testutil/util.go CheckCondition: condition present, True,
    and (optionally) with the given reason."""
    c = get_condition(job, cond_type)
    if c is None or c.status != "True":
        return False
    return (not reason) or c.reason == reason


def parse_ps_worker_log(text: str):
    """(first, last) windowed loss means from a dist_mnist_ps worker log
    ('done: first=X last=Y') — the ONE parser for every suite that
    asserts async-PS convergence."""
    first = float(text.split("first=")[1].split(" ")[0])
    last = float(text.split("last=")[1].splitlines()[0])
    return first, last
