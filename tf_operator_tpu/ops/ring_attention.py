"""Ring attention: context/sequence parallelism over the ``sp`` mesh axis.

The idiomatic TPU approach to long context (SURVEY §5 "long-context"):
each device holds one sequence block of Q/K/V; K/V blocks rotate around
the ring via ``ppermute`` (ICI neighbor transfers) while each device
accumulates its queries' attention with an online (flash-style) softmax.
Compute overlaps communication naturally — the ppermute for step t+1 is
issued with step t's compute in flight under XLA's async collectives.

Memory per device is O(S/n · S/n) per step instead of O(S²); the full
sequence never materializes anywhere. Causality is enforced with global
positions, so devices skip blocks that are entirely in their future.

Reference: Liu et al., "Ring Attention with Blockwise Transformers"
(PAPERS.md); this implementation is written fresh for shard_map.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tf_operator_tpu.compat import shard_map


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True) -> jax.Array:
    """SPMD collective attention; call inside shard_map/pjit-manual region.

    q/k/v: per-device sequence blocks [B, S_blk, H, D] (block i of the
    global sequence on ring position i). Returns [B, S_blk, H, D].
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = d ** -0.5

    def step(carry, _):
        k_blk, v_blk, src_idx, num, den, m = carry

        # bf16 operands on the MXU, f32 accumulation (a f32 einsum would
        # run the MXU at 1/4 rate for no extra attention accuracy).
        logits = jnp.einsum("bshd,bthd->bhst", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my_idx * s_blk + jnp.arange(s_blk)
            k_pos = src_idx * s_blk + jnp.arange(s_blk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -1e30)
            block_visible = src_idx <= my_idx
        else:
            block_visible = jnp.bool_(True)

        blk_max = jnp.max(logits, axis=-1)                      # [B,H,S]
        m_new = jnp.maximum(m, blk_max)
        # Guard fully-masked blocks: keep m finite so exp() stays sane.
        m_safe = jnp.maximum(m_new, -1e30 / 2)
        p = jnp.exp(logits - m_safe[..., None])                 # [B,H,S,T]
        corr = jnp.exp(m - m_safe)                              # [B,H,S]
        # corr is [B,H,S]; num is [B,S,H,D] -> align as [B,S,H,1]
        corr_bs = corr.transpose(0, 2, 1)[..., None]
        num_upd = (num * corr_bs
                   + jnp.einsum("bhst,bthd->bshd", p.astype(v_blk.dtype),
                                v_blk, preferred_element_type=jnp.float32))
        den_upd = den * corr + jnp.sum(p, axis=-1)

        num = jnp.where(block_visible, num_upd, num)
        den = jnp.where(block_visible, den_upd, den)
        m = jnp.where(block_visible, m_safe, m)

        # Rotate K/V to the next ring position (receive from left neighbor).
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src_idx = jax.lax.ppermute(src_idx, axis_name, perm)
        return (k_blk, v_blk, src_idx, num, den, m), None

    num0 = jnp.zeros((b, s_blk, h, d), jnp.float32)
    den0 = jnp.zeros((b, h, s_blk), jnp.float32)
    m0 = jnp.full((b, h, s_blk), -1e30, jnp.float32)
    carry0 = (k, v, my_idx, num0, den0, m0)
    (k_f, v_f, _, num, den, m), _ = jax.lax.scan(
        step, carry0, None, length=axis_size)

    # den layout [B,H,S] -> [B,S,H,1]
    out = num / jnp.maximum(den, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q: jax.Array, k: jax.Array,
                           v: jax.Array, causal: bool = True,
                           axis_name: str = "sp",
                           batch_axes=("dcn", "dp", "fsdp"),
                           head_axis: Optional[str] = "tp",
                           impl: str = "auto") -> jax.Array:
    """Convenience wrapper: global [B, S, H, D] arrays -> ring attention
    with S sharded over ``axis_name`` (and B/H over the data/tp axes).

    ``impl``: "flash" runs the pallas kernel per ring block (measured
    3-5x faster than the einsum ring single-chip), "einsum" is the
    original blockwise-softmax ring, "auto" picks flash when the
    per-device block shape supports it.
    """
    from tf_operator_tpu.ops import flash_attention as fa

    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(batch, axis_name, head_axis, None)
    if impl == "auto":
        sp = mesh.shape.get(axis_name, 1)
        s_blk, d = q.shape[1] // max(sp, 1), q.shape[3]
        bq, bk = fa._fit_block(s_blk, 512), fa._fit_block(s_blk, 1024)
        impl = ("flash" if fa.flash_supported(s_blk, s_blk, d, bq, bk)
                and q.shape[2] % k.shape[2] == 0 else "einsum")
    if impl == "einsum" and k.shape[2] != q.shape[2]:
        # The einsum ring needs full-head KV (the flash ring reads the
        # shared GQA head directly); repeat rather than crash deep in
        # shard_map with an einsum shape error.
        from tf_operator_tpu.ops.layers import repeat_kv

        group = q.shape[2] // k.shape[2]
        k = repeat_kv(k, group)
        v = repeat_kv(v, group)
    inner = (functools.partial(ring_flash_attention, axis_name=axis_name,
                               causal=causal) if impl == "flash"
             else functools.partial(ring_attention, axis_name=axis_name,
                                    causal=causal))
    fn = shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Ring FLASH attention: the pallas flash kernel per ring block
# ---------------------------------------------------------------------------
#
# The einsum ring above materializes an [S_blk, S_blk] score tile per
# step (measured 3-5x slower than flash single-chip). This variant runs
# the flash kernel on every (q_block, kv_block) pair and merges the
# normalized per-block outputs with their logsumexp statistics — the
# full ring-flash algorithm:
#
# - step 0 computes the diagonal block with in-block causal masking;
# - steps 1..n-1 rotate K/V one hop and run the kernel NON-causally
#   (identical static kernel parameters on every rank keeps SPMD
#   lock-step); visibility of an off-diagonal block under causality is
#   a whole-block predicate (src < my), applied as a traced mask on the
#   block's (out, lse) — masked blocks merge with weight exp(-1e30)=0;
# - backward re-runs the ring with the per-pair flash backward
#   (_bwd_impl) against the FINAL lse/delta; dK/dV accumulators rotate
#   WITH their K/V blocks and take one final hop home.

_NEG_INF = -1e30


def _merge_block(acc_o, acc_lse, o, lse, visible):
    """Fold one normalized block result into the running (out, lse)."""
    lse = jnp.where(visible, lse, _NEG_INF)
    o = jnp.where(visible, o.astype(jnp.float32), 0.0)
    m = jnp.maximum(acc_lse, lse)
    m_safe = jnp.maximum(m, _NEG_INF / 2)   # both masked: keep exp sane
    w_acc = jnp.exp(acc_lse - m_safe)
    w_new = jnp.exp(lse - m_safe)
    denom = jnp.maximum(w_acc + w_new, 1e-30)
    out = (acc_o * w_acc[..., None] + o * w_new[..., None]) \
        / denom[..., None]
    return out, m_safe + jnp.log(denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                  block_k, interpret)
    return out


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k,
                         interpret):
    from tf_operator_tpu.ops import flash_attention as fa

    qh = q.transpose(0, 2, 1, 3)   # [B,H,S,D]
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0, lse0 = fa._fwd(qh, kh, vh, causal, 0, block_q, block_k, interpret)
    acc_o = o0.astype(jnp.float32)
    acc_lse = lse0[..., 0]

    def step(carry, _):
        k_blk, v_blk, src, acc_o, acc_lse = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src = jax.lax.ppermute(src, axis_name, perm)
        o, lse = fa._fwd(qh, k_blk, v_blk, False, 0, block_q, block_k,
                         interpret)
        visible = (src < my) if causal else jnp.bool_(True)
        acc_o, acc_lse = _merge_block(acc_o, acc_lse, o, lse[..., 0],
                                      visible)
        return (k_blk, v_blk, src, acc_o, acc_lse), None

    carry = (kh, vh, my, acc_o, acc_lse)
    if n > 1:
        carry, _ = jax.lax.scan(step, carry, None, length=n - 1)
    _, _, _, acc_o, acc_lse = carry
    out_h = acc_o.astype(q.dtype)            # [B,H,S,D]
    return out_h.transpose(0, 2, 1, 3), (qh, kh, vh, out_h, acc_lse)


def _ring_flash_fwd(q, k, v, axis_name, causal, block_q, block_k,
                    interpret):
    return _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                block_k, interpret)


def _ring_flash_bwd(axis_name, causal, block_q, block_k, interpret, res,
                    do):
    from tf_operator_tpu.ops import flash_attention as fa

    qh, kh, vh, out_h, lse = res
    do_h = do.transpose(0, 2, 1, 3)
    lse_p = jnp.broadcast_to(lse[..., None], lse.shape + (fa._SUBS,))
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0, dk0, dv0 = fa._bwd_impl(qh, kh, vh, out_h, lse_p, do_h, causal,
                                 0, block_q, block_k, interpret)
    dq_acc = dq0.astype(jnp.float32)

    def step(carry, _):
        k_blk, v_blk, dk_blk, dv_blk, src, dq_acc = carry
        # dK/dV accumulators ride the ring with their blocks.
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        src = jax.lax.ppermute(src, axis_name, perm)
        dq_c, dk_c, dv_c = fa._bwd_impl(qh, k_blk, v_blk, out_h, lse_p,
                                        do_h, False, 0, block_q, block_k,
                                        interpret)
        visible = (src < my) if causal else jnp.bool_(True)
        zero = jnp.zeros((), jnp.float32)
        dq_acc = dq_acc + jnp.where(visible, dq_c.astype(jnp.float32),
                                    zero)
        dk_blk = dk_blk + jnp.where(visible, dk_c.astype(jnp.float32),
                                    zero)
        dv_blk = dv_blk + jnp.where(visible, dv_c.astype(jnp.float32),
                                    zero)
        return (k_blk, v_blk, dk_blk, dv_blk, src, dq_acc), None

    carry = (kh, vh, dk0.astype(jnp.float32), dv0.astype(jnp.float32),
             my, dq_acc)
    if n > 1:
        carry, _ = jax.lax.scan(step, carry, None, length=n - 1)
    _, _, dk_rot, dv_rot, _, dq_acc = carry
    # n-1 hops leave each block's accumulator one hop from home.
    if n > 1:
        dk_rot = jax.lax.ppermute(dk_rot, axis_name, perm)
        dv_rot = jax.lax.ppermute(dv_rot, axis_name, perm)

    dq = dq_acc.astype(qh.dtype).transpose(0, 2, 1, 3)
    dk = dk_rot.astype(kh.dtype).transpose(0, 2, 1, 3)
    dv = dv_rot.astype(vh.dtype).transpose(0, 2, 1, 3)
    return dq, dk, dv


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str = "sp", causal: bool = True,
                         block_q: int = 512, block_k: int = 1024,
                         interpret: Optional[bool] = None) -> jax.Array:
    """Ring attention with the pallas flash kernel per block; call
    inside shard_map. Same contract as ``ring_attention`` ([B, S_blk,
    H, D] per-device blocks) plus native GQA (k/v may carry fewer
    heads). Requires flash-supported block shapes."""
    from tf_operator_tpu.ops import flash_attention as fa

    s_blk, d = q.shape[1], q.shape[3]
    bq = fa._fit_block(s_blk, block_q)
    bk = fa._fit_block(s_blk, block_k)
    if not fa.flash_supported(s_blk, s_blk, d, bq, bk):
        raise ValueError(
            f"ring_flash_attention unsupported for block shape "
            f"{q.shape}; use the einsum ring (ring_attention, or "
            "ring_attention_sharded(impl='einsum'))")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA head counts must divide: q heads {q.shape[2]}, "
            f"kv heads {k.shape[2]}")
    if interpret is None:
        interpret = not fa.on_tpu()
    return _ring_flash(q, k, v, axis_name, causal, bq, bk, interpret)
