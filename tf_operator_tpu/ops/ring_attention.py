"""Ring attention: context/sequence parallelism over the ``sp`` mesh axis.

The idiomatic TPU approach to long context (SURVEY §5 "long-context"):
each device holds one sequence block of Q/K/V; K/V blocks rotate around
the ring via ``ppermute`` (ICI neighbor transfers) while each device
accumulates its queries' attention with an online (flash-style) softmax.
Compute overlaps communication naturally — the ppermute for step t+1 is
issued with step t's compute in flight under XLA's async collectives.

Memory per device is O(S/n · S/n) per step instead of O(S²); the full
sequence never materializes anywhere. Causality is enforced with global
positions, so devices skip blocks that are entirely in their future.

Reference: Liu et al., "Ring Attention with Blockwise Transformers"
(PAPERS.md); this implementation is written fresh for shard_map.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True) -> jax.Array:
    """SPMD collective attention; call inside shard_map/pjit-manual region.

    q/k/v: per-device sequence blocks [B, S_blk, H, D] (block i of the
    global sequence on ring position i). Returns [B, S_blk, H, D].
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_blk, h, d = q.shape
    scale = d ** -0.5

    def step(carry, _):
        k_blk, v_blk, src_idx, num, den, m = carry

        # bf16 operands on the MXU, f32 accumulation (a f32 einsum would
        # run the MXU at 1/4 rate for no extra attention accuracy).
        logits = jnp.einsum("bshd,bthd->bhst", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my_idx * s_blk + jnp.arange(s_blk)
            k_pos = src_idx * s_blk + jnp.arange(s_blk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -1e30)
            block_visible = src_idx <= my_idx
        else:
            block_visible = jnp.bool_(True)

        blk_max = jnp.max(logits, axis=-1)                      # [B,H,S]
        m_new = jnp.maximum(m, blk_max)
        # Guard fully-masked blocks: keep m finite so exp() stays sane.
        m_safe = jnp.maximum(m_new, -1e30 / 2)
        p = jnp.exp(logits - m_safe[..., None])                 # [B,H,S,T]
        corr = jnp.exp(m - m_safe)                              # [B,H,S]
        # corr is [B,H,S]; num is [B,S,H,D] -> align as [B,S,H,1]
        corr_bs = corr.transpose(0, 2, 1)[..., None]
        num_upd = (num * corr_bs
                   + jnp.einsum("bhst,bthd->bshd", p.astype(v_blk.dtype),
                                v_blk, preferred_element_type=jnp.float32))
        den_upd = den * corr + jnp.sum(p, axis=-1)

        num = jnp.where(block_visible, num_upd, num)
        den = jnp.where(block_visible, den_upd, den)
        m = jnp.where(block_visible, m_safe, m)

        # Rotate K/V to the next ring position (receive from left neighbor).
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src_idx = jax.lax.ppermute(src_idx, axis_name, perm)
        return (k_blk, v_blk, src_idx, num, den, m), None

    num0 = jnp.zeros((b, s_blk, h, d), jnp.float32)
    den0 = jnp.zeros((b, h, s_blk), jnp.float32)
    m0 = jnp.full((b, h, s_blk), -1e30, jnp.float32)
    carry0 = (k, v, my_idx, num0, den0, m0)
    (k_f, v_f, _, num, den, m), _ = jax.lax.scan(
        step, carry0, None, length=axis_size)

    # den layout [B,H,S] -> [B,S,H,1]
    out = num / jnp.maximum(den, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q: jax.Array, k: jax.Array,
                           v: jax.Array, causal: bool = True,
                           axis_name: str = "sp",
                           batch_axes=("dcn", "dp", "fsdp"),
                           head_axis: Optional[str] = "tp") -> jax.Array:
    """Convenience wrapper: global [B, S, H, D] arrays -> ring attention
    with S sharded over ``axis_name`` (and B/H over the data/tp axes)."""
    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(batch, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
