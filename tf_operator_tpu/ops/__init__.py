"""Neural-net ops: norms, rotary embeddings, attention (reference impl,
ring/context-parallel, and pallas TPU kernels)."""
