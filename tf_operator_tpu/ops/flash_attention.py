"""Pallas TPU flash attention (forward + backward kernels).

The attention hot path for the model harness (the compute plane the
reference delegated to user containers — SURVEY §2.3). Design targets
the MXU/VMEM structure from the pallas guide:

- online-softmax forward: Q blocks stay resident in VMEM while K/V
  blocks stream through; the S×T score matrix never hits HBM
  (O(block_q · block_k) VMEM instead of O(S·T) HBM);
- causal blocks that are entirely masked are skipped (`pl.when` on the
  block-visibility predicate), halving causal FLOPs;
- all matmuls run on the MXU with f32 accumulation
  (`preferred_element_type`), activations stay in the input dtype
  (bf16 in the real configs) on the HBM side;
- backward recomputes scores from the saved logsumexp (flash-style):
  one kernel accumulates dQ over K blocks, one accumulates dK/dV over
  Q blocks — no attention matrix is ever materialized.

Falls back to the XLA reference implementation (`ops.layers.attention`)
off-TPU or for shapes that do not tile (`flash_supported`).

Measured on the round-1 bench chip (docs/benchmarks.md): 1.17x over XLA
attention fwd+bwd at S=2048 and 2.1x end-to-end on a 570M-param decoder
train step (41% vs 21% model MFU) — the S^2 score matrix never touching
HBM is what matters on bandwidth-limited parts.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tf_operator_tpu.compat import shard_map

# jax renamed TPUCompilerParams -> CompilerParams; accept both so the
# kernel runs against either side of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

NEG_INF = -1e30
_LANES = 128
# Default tile sizes (swept in round 2: 512/1024 beat 128-blocks 2x on
# the bench chip — grid overhead; benchmarks/sweep_flash.py re-measures
# the full fwd/bwd grid so the claim stays testable per-platform).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# Row statistics (lse, delta) are carried as [..., S, _SUBS] instead of
# [..., S]: TPU blocks need their last two dims (sublanes, lanes) either
# 8/128-aligned or equal to the array dims, so a (block_q,) row vector
# cannot be a block on its own. Width-8 broadcast keeps the tile legal
# at 8x memory (a few MB) instead of 128x.
_SUBS = 8


def _dot(a, b, trans_b=False):
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dimension_numbers=dims,
                               preferred_element_type=jnp.float32)


def _causal_mask(scores, i, j, block_q, block_k, q_offset):
    """Mask scores (block_q, block_k) at q block i / k block j."""
    q_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) \
        + i * block_q + q_offset
    k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) \
        + j * block_k
    return jnp.where(q_pos >= k_pos, scores, NEG_INF)


def _block_visible(i, j, block_q, block_k, q_offset, causal):
    """Causal: k block j contributes to q block i iff its first key
    position <= the block's last query position."""
    if not causal:
        return jnp.bool_(True)
    return j * block_k <= i * block_q + q_offset + block_q - 1


def _scores(q_ref, k_ref, i, j, scale, block_q, block_k, q_offset, causal):
    """Recompute the (block_q, block_k) f32 score block."""
    s = _dot(q_ref[0, 0], k_ref[0, 0], trans_b=True) * scale
    if causal:
        s = _causal_mask(s, i, j, block_q, block_k, q_offset)
    return s


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l, *,
                causal: bool, scale: float, block_q: int, block_k: int,
                q_offset: int):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m[...] = jnp.full_like(m, NEG_INF)
        l[...] = jnp.zeros_like(l)
        acc[...] = jnp.zeros_like(acc)

    @pl.when(_block_visible(i, j, block_q, block_k, q_offset, causal))
    def _compute():
        s = _scores(q_ref, k_ref, i, j, scale, block_q, block_k,
                    q_offset, causal)                   # (bq, bk) f32
        m_prev = m[:, 0]                                # (bq,)
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1))
        corr = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, None])                # (bq, bk) f32
        l[...] = jnp.broadcast_to(
            (l[:, 0] * corr + jnp.sum(p, axis=1))[:, None], l.shape)
        m[...] = jnp.broadcast_to(m_next[:, None], m.shape)
        acc[...] = acc[...] * corr[:, None] + _dot(
            p.astype(v_ref.dtype), v_ref[0, 0])

    @pl.when(j == nk - 1)
    def _finalize():
        l_safe = jnp.where(l[:, 0] == 0.0, 1.0, l[:, 0])
        o_ref[0, 0] = (acc[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            (m[:, 0] + jnp.log(l_safe))[:, None], lse_ref[0, 0].shape)


def _fwd(q, k, v, causal, q_offset, block_q, block_k, interpret
         ) -> Tuple[jax.Array, jax.Array]:
    """q: [B, H, S, D]; k/v: [B, Hkv, S, D] with H % Hkv == 0 (GQA:
    each group of H//Hkv query heads reads one shared KV head — the
    kernel indexes it directly, so KV is never repeated in HBM).
    Returns (out [B,H,S,D], lse [B,H,S,_SUBS])."""
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    nq, nk = sq // block_q, sk // block_k
    scale = d ** -0.5
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k,
                               q_offset=q_offset)
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, _SUBS),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _SUBS), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, causal: bool, scale: float, block_q: int,
               block_k: int, q_offset: int):
    i, j = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(_block_visible(i, j, block_q, block_k, q_offset, causal))
    def _compute():
        s = _scores(q_ref, k_ref, i, j, scale, block_q, block_k,
                    q_offset, causal)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])            # (bq, bk) f32
        dp = _dot(do_ref[0, 0], v_ref[0, 0], trans_b=True)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dq_acc[...] += _dot(ds.astype(k_ref.dtype), k_ref[0, 0])

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                scale: float, block_q: int, block_k: int, q_offset: int,
                group: int):
    # Grid: (b, h_kv, nk, nq*group) — the innermost dim walks every
    # (q block, group member) pair so dK/dV accumulate over the whole
    # query-head group sharing this KV head (GQA).
    j, t = pl.program_id(2), pl.program_id(3)
    inner = pl.num_programs(3)
    i = t // group

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_block_visible(i, j, block_q, block_k, q_offset, causal))
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        s = _scores(q_ref, k_ref, i, j, scale, block_q, block_k,
                    q_offset, causal)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])            # (bq, bk) f32
        dv_acc[...] += _dot(p.astype(do.dtype).T, do)
        dp = _dot(do, v_ref[0, 0], trans_b=True)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dk_acc[...] += _dot(ds.astype(q.dtype).T, q)

    @pl.when(t == inner - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, out, lse, do, causal, q_offset, block_q, block_k,
              interpret):
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    nq, nk = sq // block_q, sk // block_k
    scale = d ** -0.5
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1)[..., None], (b, h, sq, _SUBS))   # [B,H,S,_SUBS]

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM)
    rowq = pl.BlockSpec((1, 1, block_q, _SUBS),
                        lambda b, h, i, j: (b, h, i, 0),
                        memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          q_offset=q_offset),
        grid=(b, h, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)[0]

    # dK/dV: grid over KV heads; the inner dim walks (q block, group
    # member) pairs so every query head sharing this KV head accumulates.
    def q_head(h, t):
        return h * group + t % group

    qspec_t = pl.BlockSpec((1, 1, block_q, d),
                           lambda b, h, j, t: (b, q_head(h, t), t // group, 0),
                           memory_space=pltpu.VMEM)
    kspec_t = pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, t: (b, h, j, 0),
                           memory_space=pltpu.VMEM)
    rowq_t = pl.BlockSpec((1, 1, block_q, _SUBS),
                          lambda b, h, j, t: (b, q_head(h, t), t // group, 0),
                          memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          q_offset=q_offset, group=group),
        grid=(b, h_kv, nk, nq * group),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, rowq_t, rowq_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, q_offset, block_q, block_k, interpret):
    # BOTH kernel outputs (out, lse) are primal outputs so a remat
    # policy can save them by name and elide the whole kernel from the
    # backward recompute — with out alone, lse (a backward residual)
    # would force a second forward execution under remat (round-5
    # roofline: that re-execution was ~7% of the Llama step).
    #
    # CONTRACT: lse is an auxiliary, NON-DIFFERENTIABLE output — it
    # exists for remat residual reuse, and _flash_bwd DISCARDS its
    # cotangent, so differentiating through it trains with silent zero
    # grads. (custom_vjp symbolic_zeros would let _flash_bwd assert the
    # cotangent is structurally zero, but it is unsupported under
    # shard_map, which the sharded path requires.) Anything that
    # surfaces lse beyond this module must route it through
    # _guard_lse_nondiff so a differentiating caller fails loudly;
    # tests/test_flash_attention.py pins both the guard and this
    # discard contract.
    return _fwd(q, k, v, causal, q_offset, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, q_offset, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _fwd(q, k, v, causal, q_offset, block_q, block_k, interpret)
    # Tag the kernel outputs on the AD path: under
    # jax.checkpoint(policy=save_only_these_names("flash_out",
    # "flash_lse")) the linearized jaxpr keeps exactly these residuals
    # on the known side, so the backward pass reuses them instead of
    # re-running the kernel (round-5 roofline: the re-execution was
    # ~7% of the 570M Llama step). A no-op under any other policy.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd(causal, q_offset, block_q, block_k, interpret, res, cots):
    q, k, v, out, lse = res
    # lse is auxiliary: its cotangent is DISCARDED (contract at _flash).
    do, _dlse = cots
    dq, dk, dv = _bwd_impl(q, k, v, out, lse, do, causal, q_offset,
                           block_q, block_k, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


@jax.custom_vjp
def _guard_lse_nondiff(lse):
    """Identity gate for exposing lse outside this module: reverse-mode
    differentiating anything built on the gated value raises at trace
    time instead of silently flowing the zero cotangent _flash_bwd
    discards."""
    return lse


def _guard_lse_fwd(lse):
    return lse, None


def _guard_lse_bwd(_, g):
    raise NotImplementedError(
        "flash lse is a non-differentiable auxiliary output (saved for "
        "remat residual reuse); _flash_bwd discards its cotangent, so "
        "gradients through lse would silently be zero. Implement the "
        "lse cotangent in _bwd_impl before differentiating through it.")


_guard_lse_nondiff.defvjp(_guard_lse_fwd, _guard_lse_bwd)


def _fit_block(seq: int, want: int) -> int:
    """Largest 8-aligned block <= ``want`` that divides ``seq`` (so any
    8-aligned sequence keeps the flash path; big blocks only where they
    fit — grid overhead made 128-blocks 2x slower than 512/1024 on the
    bench chip, but S=1536 etc. must not fall back to XLA)."""
    b = min(want, seq)
    b -= b % 8
    while b > 8 and seq % b:
        b -= 8
    return b


def flash_supported(q_seq: int, k_seq: int, head_dim: int,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> bool:
    """Shapes must tile into sublane-aligned blocks; head_dim must fill
    MXU lanes."""
    bq, bk = _fit_block(q_seq, block_q), _fit_block(k_seq, block_k)
    if bq < 8 or bk < 8:
        # Degenerate sequences (< 8, e.g. single-token decode) cannot
        # form a sublane-aligned block — fall back instead of dividing
        # by the zero block _fit_block returns.
        return False
    return (q_seq % bq == 0 and bq % 8 == 0
            and k_seq % bk == 0 and bk % 8 == 0
            and head_dim % _LANES == 0 and head_dim <= 512)


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, q_offset: int = 0,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Flash attention over [B, S, H, D] tensors (same layout as
    ``ops.layers.attention``). GQA: k/v may carry fewer heads
    [B, S, Hkv, D] with H % Hkv == 0 — the kernel reads the shared KV
    head directly instead of requiring a repeated copy in HBM.
    Requires `flash_supported` shapes."""
    bq = _fit_block(q.shape[1], block_q)
    bk = _fit_block(k.shape[1], block_k)
    if not flash_supported(q.shape[1], k.shape[1], q.shape[3], bq, bk):
        raise ValueError(
            f"flash_attention unsupported for shapes q={q.shape} "
            f"k={k.shape} (blocks {bq}/{bk}); use ops.layers.attention")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA head counts must divide: q heads {q.shape[2]}, "
            f"kv heads {k.shape[2]}")
    qt = q.transpose(0, 2, 1, 3)   # [B,H,S,D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, _lse = _flash(qt, kt, vt, causal, q_offset, bq, bk, interpret)
    return out.transpose(0, 2, 1, 3)


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            mesh, causal: bool = True, q_offset: int = 0,
                            head_axis: str = "tp",
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool = False) -> jax.Array:
    """Flash attention under GSPMD: a pallas_call is an opaque custom
    call with no partitioning rule, so inside a sharded jit it must go
    through shard_map — batch over the data axes, heads over tp, the
    sequence unsharded per shard (use ring attention when sp > 1)."""
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.parallel.mesh import data_axes

    spec = P(data_axes(mesh), None,
             head_axis if head_axis in mesh.axis_names else None, None)
    fn = shard_map(
        functools.partial(flash_attention, causal=causal,
                          q_offset=q_offset, block_q=block_q,
                          block_k=block_k, interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def best_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True, q_offset: int = 0,
                   mesh=None, force_flash: bool = False,
                   block_q: int = DEFAULT_BLOCK_Q,
                   block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Dispatch: pallas flash on TPU when shapes tile (through shard_map
    when a mesh is active so GSPMD can partition it), else the XLA
    reference. Accepts GQA kv (fewer heads); the XLA fallback repeats
    KV to full heads itself. ``force_flash`` always takes the pallas
    path (interpret mode off-TPU) — shape errors surface instead of
    falling back."""
    from tf_operator_tpu.ops.layers import attention, repeat_kv

    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA head counts must divide: q heads {q.shape[2]}, "
            f"kv heads {k.shape[2]}")
    sp_size = 1 if mesh is None else mesh.shape.get("sp", 1)
    tp_size = 1 if mesh is None else mesh.shape.get("tp", 1)
    # Under a mesh the head axis of q AND k/v is sharded over tp, so
    # both head counts must divide tp for the shard_map specs to be
    # legal (llama_3_8b kv=8, tp=16 would otherwise crash in shard_map
    # instead of falling back).
    auto_ok = (on_tpu() and sp_size == 1
               and q.shape[2] % tp_size == 0
               and k.shape[2] % tp_size == 0
               and flash_supported(q.shape[1], k.shape[1], q.shape[3],
                                   block_q, block_k))
    if force_flash or auto_ok:
        interpret = not on_tpu()
        if mesh is not None:
            if k.shape[2] % tp_size:
                # forced-flash with tp-indivisible GQA KV: repeat to
                # full heads so the head sharding stays legal.
                group = q.shape[2] // k.shape[2]
                k, v = repeat_kv(k, group), repeat_kv(v, group)
            return flash_attention_sharded(q, k, v, mesh, causal=causal,
                                           q_offset=q_offset,
                                           block_q=block_q,
                                           block_k=block_k,
                                           interpret=interpret)
        return flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    group = q.shape[2] // k.shape[2]
    if group > 1:
        k, v = repeat_kv(k, group), repeat_kv(v, group)
    return attention(q, k, v, causal=causal, q_offset=q_offset)
