"""Core layers: RMSNorm, rotary position embeddings, attention.

TPU-first choices: bfloat16 activations with float32 accumulation
(``preferred_element_type``) so matmuls land on the MXU at full rate;
shapes kept static and lane-aligned (head_dim/mlp multiples of 128 in
real configs) so XLA tiles cleanly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in f32 for numerical stability, cast back to input dtype."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    out = x * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float = 10000.0) -> jax.Array:
    """[max_seq_len, head_dim//2] complex rotation angles."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    return jnp.outer(t, inv_freq)  # [S, D/2]


def apply_rope(x: jax.Array, angles: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotate [..., S, H, D] by position. ``angles`` is [max_S, D/2];
    ``positions`` ([..., S], e.g. [S] or [B, S] for per-row offsets on
    the decode path) defaults to arange."""
    seq_len = x.shape[-3]
    if positions is None:
        freqs = angles[:seq_len]  # [S, D/2]
    else:
        freqs = angles[positions]  # [..., S, D/2]
    # [..., S, 1, D/2]: the inserted head axis broadcasts against H for
    # both the [S, D/2] and per-row [B, S, D/2] shapes.
    cos = jnp.cos(freqs)[..., :, None, :]
    sin = jnp.sin(freqs)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Grouped-query attention: repeat KV heads to match Q heads.
    [..., S, KVH, D] -> [..., S, KVH*n_rep, D]."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True,
              mask: Optional[jax.Array] = None,
              q_offset: int = 0) -> jax.Array:
    """Reference (non-pallas) attention.

    q: [B, S, H, D], k/v: [B, T, H, D] -> [B, S, H, D]. Softmax in f32.
    ``q_offset`` shifts query positions for causal masking (ring/context
    parallel blocks and decode).
    """
    *_, s, h, d = q.shape
    t = k.shape[-3]
    scale = d ** -0.5
    logits = jnp.einsum("...shd,...thd->...hst", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = jnp.arange(s) + q_offset
        k_pos = jnp.arange(t)
        causal_mask = q_pos[:, None] >= k_pos[None, :]  # [S, T]
        logits = jnp.where(causal_mask[None, None, :, :], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("...hst,...thd->...shd", weights, v)


def make_tpu_batch_norm():
    """Define the flax TPUBatchNorm module (deferred so this module keeps
    its jax-only import surface; models import flax anyway)."""
    import flax.linen as nn

    class _TPUBatchNorm(nn.Module):
        """BatchNorm formulated for the TPU cost structure.

        Differences from ``flax.linen.BatchNorm`` that matter on parts
        where the VPU/reduce rate — not the MXU — bounds ResNet steps
        (BASELINE.md platform characterization):

        - ``stats_dtype`` controls the statistics accumulation dtype.
          f32 (default) matches flax; bf16 skips the convert half of the
          convert+reduce fusions that dominate the profiled step.
        - normalization folds to one per-channel affine ``y = x*a + b``
          with ``a = scale/sqrt(var+eps)``, ``b = bias - mean*a``
          computed in f32 on the tiny [C] vectors, so the big-tensor op
          is a single fused multiply-add in the activation dtype (XLA
          fuses it into the producing conv's epilogue).
        - ``use_running_average=True`` makes the layer a pure affine
          read of stored statistics — the building block for interval /
          frozen statistics schemes (stats every N steps).

        Variance uses E[x²]−E[x]² (one fused pass instead of a second
        centered pass — flax ``use_fast_variance`` semantics).
        """

        use_running_average: bool = False
        momentum: float = 0.9
        epsilon: float = 1e-5
        dtype: object = None
        param_dtype: object = jnp.float32
        stats_dtype: object = jnp.float32
        scale_init: object = nn.initializers.ones
        track_stats: bool = True

        @nn.compact
        def __call__(self, x: jax.Array) -> jax.Array:
            feat = x.shape[-1]
            scale = self.param("scale", self.scale_init, (feat,),
                               self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, (feat,),
                              self.param_dtype)
            if self.track_stats:
                ra_mean = self.variable(
                    "batch_stats", "mean",
                    lambda: jnp.zeros((feat,), jnp.float32))
                ra_var = self.variable(
                    "batch_stats", "var",
                    lambda: jnp.ones((feat,), jnp.float32))
            if self.use_running_average or self.is_initializing():
                if self.track_stats:
                    mean, var = ra_mean.value, ra_var.value
                else:
                    # Frozen unit statistics: a pure per-channel affine
                    # (the norm-free ceiling probe) — zero reduces.
                    mean = jnp.zeros((feat,), jnp.float32)
                    var = jnp.ones((feat,), jnp.float32)
            else:
                axes = tuple(range(x.ndim - 1))
                xs = x.astype(self.stats_dtype)
                mean = jnp.mean(xs, axis=axes)
                var = jnp.mean(jnp.square(xs), axis=axes) \
                    - jnp.square(mean)
                mean = mean.astype(jnp.float32)
                var = jnp.maximum(var.astype(jnp.float32), 0.0)
                if self.track_stats:
                    ra_mean.value = (self.momentum * ra_mean.value
                                     + (1.0 - self.momentum) * mean)
                    ra_var.value = (self.momentum * ra_var.value
                                    + (1.0 - self.momentum) * var)
            a = scale.astype(jnp.float32) * jax.lax.rsqrt(
                var + self.epsilon)
            b = bias.astype(jnp.float32) - mean * a
            out_dtype = self.dtype or x.dtype
            if out_dtype == jnp.float32:
                return x.astype(jnp.float32) * a + b
            return x * a.astype(out_dtype) + b.astype(out_dtype)

    return _TPUBatchNorm


_tpu_bn_cls = None


def tpu_batch_norm(**kwargs):
    """TPUBatchNorm module instance (see make_tpu_batch_norm)."""
    global _tpu_bn_cls
    if _tpu_bn_cls is None:
        _tpu_bn_cls = make_tpu_batch_norm()
    return _tpu_bn_cls(**kwargs)
