"""Controllable worker stub — the e2e fault-injection payload.

Reference analog: test/test-server/test_app.py, the Flask app run *as*
the TF replicas in e2e so the harness can read each replica's TF_CONFIG
(`/tfconfig`) and make any replica exit with any code (`/exit`). This
stub is file-based instead of HTTP (deterministic, dependency-free):

- at startup it writes its identity + bootstrap env snapshot to
  ``$TPUJOB_STUB_DIR/{pod}.env.json``;
- it polls ``$TPUJOB_STUB_DIR/{pod}.cmd`` for a line ``exit:N`` and exits
  with code N when told;
- ``--exit-after S --exit-code N`` terminates autonomously;
- ``--train-steps N`` switches to the fake-trainer loop: one "optimizer
  step" every ``--step-seconds``, a deterministic decreasing loss line
  per step, and the real coordinated-checkpoint hook
  (``train/checkpoint.py CheckpointHook``) threaded after every step —
  periodic saves, save-before-evict barrier acks, and
  restore-with-identity run exactly as a real training loop would,
  minus jax (checkpoints are tiny JSON files). The e2e payload for the
  controller/ckpt.py drain-with-checkpoint arc.

Run as: ``python -m tf_operator_tpu.runtime.worker_stub [flags]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ENV_KEYS = (
    "TPUJOB_CLUSTER_SPEC",
    "TPU_WORKER_ID",
    "TPU_WORKER_HOSTNAMES",
    "TPU_ACCELERATOR_TYPE",
    "TPU_TOPOLOGY",
    "JAX_COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES",
    "JAX_PROCESS_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "MEGASCALE_NUM_SLICES",
    "MEGASCALE_SLICE_ID",
    "MEGASCALE_SLICE_COORDINATOR",
    "TPUJOB_POD_NAME",
    "TPUJOB_POD_NAMESPACE",
)


class FileCheckpointer:
    """Minimal ``Checkpointer`` surface (save/wait/latest_step) writing
    one JSON file per step — what the fake trainer persists instead of
    orbax state, so the coordinated-checkpoint protocol is exercised
    end-to-end without jax."""

    def __init__(self, directory: str):
        self.directory = directory

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}.json")

    def save(self, step: int, state, force: bool = False) -> bool:
        os.makedirs(self.directory, exist_ok=True)
        path = self._path(step)
        with open(path + ".tmp", "w") as f:
            json.dump({"step": step, "state": state}, f)
        os.replace(path + ".tmp", path)
        return True

    def wait(self) -> None:
        pass  # synchronous writer: durability happened in save()

    def latest_step(self):
        try:
            names = os.listdir(self.directory)
        except OSError:
            return None
        steps = [int(n[len("step_"):-len(".json")]) for n in names
                 if n.startswith("step_") and n.endswith(".json")]
        return max(steps) if steps else None


def _train(train_steps: int, step_seconds: float) -> int:
    """Fake-trainer loop: resume from the controller-committed step,
    then one deterministic step per tick with the checkpoint hook
    threaded exactly like train/trainer.py run_train_steps."""
    from tf_operator_tpu.train.checkpoint import (
        CheckpointConfig,
        CheckpointHook,
    )

    config = CheckpointConfig.from_env()
    hook = None
    step = 0
    if config.directory:
        hook = CheckpointHook(FileCheckpointer(config.directory), config)
        restore = hook.restore_step()
        if restore is not None:
            step = restore
            hook.note_restored(restore)
            print(f"resumed from checkpoint at step {restore}", flush=True)
    while step < train_steps:
        time.sleep(step_seconds)
        step += 1
        # Strictly-decreasing deterministic curve: a resume that forgot
        # its progress would print a loss the curve already passed.
        print(f"step {step} loss {100.0 / step:.4f}", flush=True)
        if hook is not None:
            hook.after_step(step, {"step": step})
    print(f"done: {train_steps} steps", flush=True)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--exit-after", type=float, default=None,
                        help="exit autonomously after this many seconds")
    parser.add_argument("--exit-code", type=int, default=0)
    parser.add_argument("--poll-interval", type=float, default=0.05)
    parser.add_argument("--train-steps", type=int, default=None,
                        help="run the fake-trainer loop to this TOTAL "
                             "step count (restores count toward it), "
                             "with the coordinated-checkpoint hook "
                             "active when TPUJOB_CKPT_DIR is set")
    parser.add_argument("--step-seconds", type=float, default=0.05,
                        help="(--train-steps) seconds per fake step")
    parser.add_argument("--term-grace", type=float, default=None,
                        help="handle SIGTERM gracefully: keep running "
                             "this many seconds, then write "
                             "{pod}.exited (with a timestamp) and exit "
                             "0 — models a slow-dying worker for "
                             "preemption-overlap tests")
    args = parser.parse_args(argv)

    stub_dir = os.environ.get("TPUJOB_STUB_DIR", "")
    pod_name = os.environ.get("TPUJOB_POD_NAME", f"pid-{os.getpid()}")

    # Install the graceful-term handler BEFORE publishing the env
    # snapshot: tests use the snapshot's existence as "stub fully
    # started", so a SIGTERM arriving after it must always be caught.
    term_at = []
    if args.term_grace is not None:
        import signal

        signal.signal(signal.SIGTERM,
                      lambda *_: term_at.append(time.monotonic()))
    # Identity banner on stdout: exercised by the log-capture path
    # (reference test-server logs requests the same way).
    print(f"worker stub {pod_name} started", flush=True)

    cmd_path = None
    if stub_dir:
        os.makedirs(stub_dir, exist_ok=True)
        snapshot = {k: os.environ[k] for k in ENV_KEYS if k in os.environ}
        snapshot["argv"] = sys.argv[1:]
        # Atomic publish: tests poll for this file and read it the
        # moment it exists; a plain open-write would expose a partial
        # JSON document to that race.
        snap_path = os.path.join(stub_dir, f"{pod_name}.env.json")
        with open(snap_path + ".tmp", "w") as f:
            json.dump(snapshot, f, indent=2, sort_keys=True)
        os.replace(snap_path + ".tmp", snap_path)
        cmd_path = os.path.join(stub_dir, f"{pod_name}.cmd")

    if args.train_steps is not None:
        return _train(args.train_steps, args.step_seconds)

    deadline = (time.monotonic() + args.exit_after
                if args.exit_after is not None else None)
    while True:
        if term_at and time.monotonic() - term_at[0] >= args.term_grace:
            # Slow graceful death complete: publish the exit instant
            # (wall clock — tests compare against other processes).
            if stub_dir:
                path = os.path.join(stub_dir, f"{pod_name}.exited")
                with open(path + ".tmp", "w") as f:
                    json.dump({"exited_at": time.time()}, f)
                os.replace(path + ".tmp", path)
            return 0
        if cmd_path and os.path.exists(cmd_path):
            with open(cmd_path) as f:
                line = f.read().strip()
            # Parse before unlinking: a partially-written file (non-atomic
            # writer) is left in place for the next poll.
            code = None
            if line.startswith("exit:"):
                try:
                    code = int(line.split(":", 1)[1])
                except ValueError:
                    code = None
            if code is not None:
                os.unlink(cmd_path)
                return code
        if deadline is not None and time.monotonic() >= deadline:
            return args.exit_code
        time.sleep(args.poll_interval)


if __name__ == "__main__":
    sys.exit(main())
