"""Runtime: workqueue, events, object store, cluster backends.

Reference parity: the client-go machinery the reference leans on (rate
limited workqueues, event recorder, informer caches) plus the data plane
the reference delegates to kubelet — rebuilt here as a process-native
runtime so the whole control loop runs hermetically.
"""
