"""Kube node agent: the DaemonSet half of checkpoint coordination.

On ``--backend kube`` the kubelet runs the containers, so the operator
has no process-level channel to the worker: a preemption notice stamped
on a pod (controller/ckpt.py save-before-evict barrier) is just an
annotation, and the worker's checkpoint state file is just a file on
some node. This agent — deployed as a DaemonSet
(manifests/base/node-agent.yaml) with the node's relay directory
hostPath-mounted — closes that loop per node, the same loop
``LocalProcessBackend`` runs for its subprocesses and
``runtime/agent.py`` runs for the served plane (both through
runtime/relay.py, so the three planes share one contract):

- **Notice relay (control plane -> worker)**: watches the pods bound to
  THIS node (name from the downward API, ``NODE_NAME`` fieldRef
  ``spec.nodeName``); when the operator stamps the
  ``tpu-operator.dev/preemption-notice`` annotation, the agent writes
  the notice atomically to the pod's ``TPUJOB_PREEMPT_FILE`` path in
  the shared relay volume, where the training loop polls it each step.
- **Checkpoint mirror (worker -> control plane)**: polls each relayed
  pod's ``TPUJOB_CKPT_FILE``; on change, PATCHes the payload onto the
  pod as the ``tpu-operator.dev/ckpt-state`` annotation. The operator's
  relay watcher (KubeOperator) converts that into the in-memory
  ``CheckpointRecord`` that barrier accounting and restore-step
  derivation consume — pod annotations are the status channel, exactly
  like kubelet phase reports.
- **Liveness**: heartbeats the ``tpu-operator.dev/agent-heartbeat``
  annotation onto its Node. The operator treats a gang as
  barrier-capable only while every hosting node's heartbeat is fresh;
  no agent (or a dead one) means barriers degrade to plain eviction
  instead of hanging a drain on acks that can never arrive.

All API writes go through ``runtime/retry.py`` ``with_retries`` —
apiserver blips back off and retry in place; only exhausted retries
surface as ``node_agent_relay_errors_total`` and are re-attempted on
the next poll tick. Nothing here kills a loop thread.

Run as: ``python -m tf_operator_tpu.runtime.nodeagent --node $NODE_NAME``.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import logging
import os
import signal
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import Pod
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import relay as relay_mod
from tf_operator_tpu.runtime import retry as retry_mod
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime import trace as trace_mod
from tf_operator_tpu.runtime.kube import KubeClient, KubeConfig, KubeInformer
from tf_operator_tpu.runtime.store import DELETED, Store

log = logging.getLogger("tpu_operator.nodeagent")

HEARTBEAT_SECONDS = 5.0
CKPT_POLL_SECONDS = 0.5

DEFAULT_RELAY_DIR = "/var/run/tpu-operator/relay"


@dataclass
class _RelayedPod:
    """Per-pod relay state. ``notice_written`` and ``ckpt_sent`` are
    dedup markers (each notice hits the file once, each ckpt payload
    hits the apiserver once); ``ckpt_mtime`` is the worker file's last
    fully-parsed st_mtime_ns."""

    pod: Pod
    notice_written: str = ""
    ckpt_mtime: int = 0
    ckpt_sent: str = ""


class KubeNodeAgent:
    """The per-node relay daemon (see module docstring). Owns a private
    Store fed by one pods informer — the same reflector machinery the
    operator uses, so apiserver hiccups get list/watch backoff for
    free."""

    def __init__(self, client: KubeClient, node_name: str, relay_dir: str,
                 namespace: Optional[str] = None,
                 heartbeat_seconds: float = HEARTBEAT_SECONDS,
                 ckpt_poll_seconds: float = CKPT_POLL_SECONDS):
        if not node_name:
            raise ValueError(
                "node agent needs its node name (downward-API NODE_NAME "
                "fieldRef spec.nodeName in the DaemonSet manifest)")
        self.client = client
        self.node = node_name
        self.relay_dir = relay_dir
        self.heartbeat_seconds = heartbeat_seconds
        self.ckpt_poll_seconds = ckpt_poll_seconds
        self.store = Store()
        # namespace=None watches all namespaces (DaemonSet semantics:
        # any tenant's pod can land on this node).
        self._informer = KubeInformer(client, self.store, store_mod.PODS,
                                      namespace=namespace)
        self._pods: Dict[Tuple[str, str], _RelayedPod] = {}
        self._lock = threading.Lock()
        self._watcher = None
        self._threads: list = []
        self._stopped = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "KubeNodeAgent":
        # First heartbeat before anything else: the operator's
        # barrier-capability gate reads it, and a gang must not sit in a
        # barrier it could have started acking.
        self._heartbeat_once()
        self._watcher = self.store.watch(store_mod.PODS, self._on_pod_event)
        self._informer.start()
        for name, target in (("nodeagent-heartbeat", self._heartbeat_loop),
                             ("nodeagent-ckpt-poll", self._poll_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info("kube node agent up on node %s (relay dir %s)",
                 self.node, self.relay_dir)
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._watcher is not None:
            self._watcher.stop()
        self._informer.stop()
        self.store.stop_watchers()
        for t in self._threads:
            t.join(timeout=5)

    # -- heartbeat ---------------------------------------------------------

    def _heartbeat_once(self) -> bool:
        stamp = _now().isoformat()

        def _patch():
            self.client.patch(
                store_mod.NODES, "", self.node,
                {"metadata": {"annotations": {
                    constants.ANNOTATION_AGENT_HEARTBEAT: stamp}}})

        try:
            retry_mod.with_retries(_patch, component="nodeagent.heartbeat")
        except Exception:
            log.warning("heartbeat for node %s failed; gangs on this node "
                        "are not barrier-capable until one lands",
                        self.node, exc_info=True)
            return False
        metrics.node_agent_heartbeats.inc(node=self.node)
        return True

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_seconds):
            self._heartbeat_once()

    # -- notice relay (annotation -> file) ---------------------------------

    def _on_pod_event(self, event_type: str, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        if event_type == DELETED:
            with self._lock:
                rp = self._pods.pop(key, None)
            # Relay files follow the pod object (kubelet log-retention
            # semantics); the dead incarnation's notice must not be
            # readable by a restart-with-identity successor.
            relay_mod.cleanup(self.relay_dir, rp.pod if rp else pod)
            return
        if pod.spec.node_name != self.node or not pod.spec.relay_dir:
            return
        with self._lock:
            rp = self._pods.get(key)
            if rp is None:
                rp = self._pods[key] = _RelayedPod(pod=pod)
            else:
                rp.pod = pod
        self._forward_notice(rp)

    def _forward_notice(self, rp: _RelayedPod) -> None:
        pod = rp.pod
        notice = pod.metadata.annotations.get(
            constants.ANNOTATION_PREEMPT_NOTICE, "")
        if not notice or rp.notice_written == notice:
            return
        with trace_mod.span(
                "nodeagent.notice_relay",
                pod=f"{pod.metadata.namespace}/{pod.metadata.name}"):
            try:
                rp.notice_written = retry_mod.with_retries(
                    lambda: relay_mod.forward_notice(
                        self.relay_dir, pod, notice, rp.notice_written),
                    component="nodeagent.notice")
            except OSError:
                metrics.node_agent_relay_errors.inc(kind="notice_write")
                log.warning("notice write for pod %s/%s failed; retrying "
                            "on the next poll", pod.metadata.namespace,
                            pod.metadata.name, exc_info=True)

    # -- checkpoint mirror (file -> annotation) ----------------------------

    def _poll_loop(self) -> None:
        while not self._stopped.wait(self.ckpt_poll_seconds):
            with self._lock:
                relayed = list(self._pods.values())
            for rp in relayed:
                # Notices retry here too: an annotation that arrived
                # while the volume was unwritable would otherwise wait
                # for a MODIFIED event that may never refire.
                self._forward_notice(rp)
                self._mirror_ckpt(rp)

    def _mirror_ckpt(self, rp: _RelayedPod) -> None:
        pod = rp.pod
        data, rp.ckpt_mtime = relay_mod.read_ckpt_file(
            relay_mod.ckpt_path(self.relay_dir, pod), rp.ckpt_mtime)
        if data is None:
            return
        payload = json.dumps(data, sort_keys=True)
        if payload == rp.ckpt_sent:
            return
        with trace_mod.span(
                "nodeagent.ckpt_relay",
                pod=f"{pod.metadata.namespace}/{pod.metadata.name}",
                step=data.get("step")):
            try:
                retry_mod.with_retries(
                    lambda: self.client.patch(
                        store_mod.PODS, pod.metadata.namespace,
                        pod.metadata.name,
                        {"metadata": {"annotations": {
                            constants.ANNOTATION_CKPT_STATE: payload}}}),
                    component="nodeagent.ckpt")
            except store_mod.NotFoundError:
                return  # pod vanished; DELETED cleanup is in flight
            except Exception:
                metrics.node_agent_relay_errors.inc(kind="ckpt_patch")
                # Rewind so the next tick re-reads and re-sends — a
                # barrier ack must not be lost to one bad PATCH.
                rp.ckpt_mtime = 0
                log.warning("ckpt-state patch for pod %s/%s failed; will "
                            "re-mirror", pod.metadata.namespace,
                            pod.metadata.name, exc_info=True)
                return
        rp.ckpt_sent = payload


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def main(argv=None) -> int:
    from tf_operator_tpu.runtime.logconfig import setup_logging

    parser = argparse.ArgumentParser(prog="tpu-node-agent-kube")
    parser.add_argument("--node", default=os.environ.get("NODE_NAME", ""),
                        help="this node's name (default $NODE_NAME, the "
                             "DaemonSet downward-API fieldRef)")
    parser.add_argument("--relay-dir",
                        default=os.environ.get("TPU_OPERATOR_RELAY_DIR",
                                               DEFAULT_RELAY_DIR),
                        help="hostPath directory shared with workload "
                             "pods (must match the operator's "
                             "--agent-relay-dir)")
    parser.add_argument("--server", default="",
                        help="apiserver URL override (tests/dev; "
                             "production resolves in-cluster config)")
    parser.add_argument("--kubeconfig", default=None,
                        help="kubeconfig path when not in-cluster")
    parser.add_argument("--namespace", default=None,
                        help="restrict the pod watch to one namespace "
                             "(default: all)")
    parser.add_argument("--heartbeat-seconds", type=float,
                        default=HEARTBEAT_SECONDS)
    parser.add_argument("--ckpt-poll-seconds", type=float,
                        default=CKPT_POLL_SECONDS)
    parser.add_argument("--json-log-format", dest="json_log", default=True,
                        action=argparse.BooleanOptionalAction)
    args = parser.parse_args(argv)
    setup_logging(json_format=args.json_log)

    if args.server:
        config = KubeConfig(server=args.server)
    else:
        config = KubeConfig.resolve(args.kubeconfig)
    agent = KubeNodeAgent(KubeClient(config), args.node, args.relay_dir,
                          namespace=args.namespace,
                          heartbeat_seconds=args.heartbeat_seconds,
                          ckpt_poll_seconds=args.ckpt_poll_seconds)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    agent.start()
    stop.wait()
    agent.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
