"""TLS bootstrap + token-file parsing for the served control plane.

The reference gets transport security and authentication for free by
riding the Kubernetes API server (every hop is TLS + bearer token + RBAC:
sdk/python/kubeflow/tfjob/api/tf_job_client.py:55-76 loads kube config,
manifests/base/cluster-role.yaml scopes the operator). The TPU-native
served control plane (runtime/apiserver.py) has no API server in front
of it, so it carries its own minimal equivalents:

- a self-signed certificate bootstrap for first-run TLS (private key
  written 0600, never world-readable — the same key-material discipline
  as runtime/kube.py's kubeconfig temp files);
- a static bearer-token file, one token per line with an optional role
  (``admin`` full access, ``read-only`` GET/watch/logs only) — the
  ServiceAccount-token + RBAC-role analog collapsed to two roles.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import logging
import os
from typing import Dict, Optional, Sequence

log = logging.getLogger("tpu_operator.tls")

ROLE_ADMIN = "admin"
ROLE_READ_ONLY = "read-only"
ROLES = (ROLE_ADMIN, ROLE_READ_ONLY)


def ensure_self_signed(cert_path: str, key_path: str,
                       common_name: str = "tpu-operator",
                       dns_names: Optional[Sequence[str]] = None,
                       ip_addresses: Optional[Sequence[str]] = None,
                       days: int = 3650) -> None:
    """Create a self-signed server certificate + key at the given paths
    if either is missing (idempotent otherwise). SANs default to
    localhost + loopback so local clients verify out of the box; pass
    the operator's service DNS name / host IPs for remote clients."""
    if os.path.exists(cert_path) and os.path.exists(key_path):
        return
    try:
        # Imported lazily: cryptography is an optional extra
        # (``pip install tf-operator-tpu[tls]``) — the operator's
        # token-auth path and every non-TLS deployment must work
        # without it, and only actual cert GENERATION needs it
        # (pre-provisioned cert/key pairs are served by the stdlib).
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError as e:
        raise RuntimeError(
            "self-signed TLS bootstrap needs the 'cryptography' package; "
            "install the tls extra (pip install tf-operator-tpu[tls]) or "
            "provide --api-tls-cert/--api-tls-key generated elsewhere"
        ) from e

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    sans: list = [x509.DNSName(d) for d in (dns_names or ["localhost"])]
    for ip in (ip_addresses or ["127.0.0.1"]):
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(ip)))
        except ValueError:
            sans.append(x509.DNSName(ip))
    now = _dt.datetime.now(_dt.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _dt.timedelta(minutes=5))
            .not_valid_after(now + _dt.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName(sans),
                           critical=False)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))

    os.makedirs(os.path.dirname(os.path.abspath(key_path)), exist_ok=True)
    # Key first, 0600 from birth (never a window where it's readable).
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    log.info("generated self-signed TLS certificate at %s (CN=%s)",
             cert_path, common_name)


def read_token(path: str) -> str:
    """First token in a token file (clients need exactly one): same
    skipping rules as load_tokens — blank lines and # comments are not
    tokens."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                return line.split()[0]
    raise ValueError(f"{path}: no token found")


def load_tokens(path: str) -> Dict[str, str]:
    """Parse a bearer-token file: one ``<token> [role]`` per line
    (role defaults to admin; blank lines and # comments skipped).
    Returns {token: role}."""
    tokens: Dict[str, str] = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            token, role = parts[0], (parts[1] if len(parts) > 1
                                     else ROLE_ADMIN)
            if role not in ROLES:
                raise ValueError(
                    f"{path}:{lineno}: unknown role {role!r} "
                    f"(expected one of {', '.join(ROLES)})")
            if token in tokens:
                raise ValueError(f"{path}:{lineno}: duplicate token")
            tokens[token] = role
    if not tokens:
        raise ValueError(f"{path}: no tokens found")
    return tokens
