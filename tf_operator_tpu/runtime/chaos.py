"""Deterministic fault injection for the control plane.

Every disruption-sensitive subsystem in this operator (slice-health
drains, quota reclaim, checkpoint barriers) was built against a
COOPERATIVE fake apiserver; real clusters at pod scale answer with
429/500 storms, write conflicts, stale reads, dropped watches, and
operator restarts mid-reconcile — the papers treat preemption/failure
as the steady state ("Exploring the limits of Concurrency in ML
Training on Google TPUs", PAPERS.md). This module makes those faults
INJECTABLE and SEEDED so convergence invariants can be asserted under
any profile, reproducibly:

- ``FaultProfile``: per-fault rates (write/read 5xx, 409 conflicts,
  timeouts, stale reads, watch drops, lost responses) with per-verb /
  per-kind overrides and a seed. Named presets: ``off``, ``default``
  (the acceptance profile: >=5% write errors + >=5% conflicts),
  ``heavy``.
- ``FaultInjector``: the seeded decision engine + per-fault counters
  (also exported as ``tpu_operator_chaos_faults_injected_total``).
- ``ChaosStore``: wraps the in-process ``Store`` with the profile on
  the OPERATOR's read/write path — the process-native twin of
  ``kube_fake.FakeKubeState``'s HTTP-level injection, used by
  ``bench_controlplane.py --chaos`` and
  ``hack/verify-chaos-invariants.py``.
- ``crash_controller``: the operator crash-restart hook — hard-stop a
  controller assembly, abandoning ALL in-memory state (workqueue
  backlog, expectations, bootstrap-hash caches, barrier deadlines,
  drain anchors) while the store (the durable plane) survives; the
  harness then cold-starts a fresh assembly against it and asserts
  convergence.

The fault vocabulary (``FAULTS``):

========== ==============================================================
write_error mutating verb answers 5xx BEFORE applying (request rejected)
lost_response mutating verb APPLIES, then the response is lost (the
            retry-idempotency hazard: a retried create now 409s, a
            retried delete 404s — both semantic outcomes callers handle)
read_error  get/list answers 5xx
conflict    update/status write answers 409 (optimistic-concurrency loss)
timeout     request hangs/drops with no response (TimeoutError /
            connection reset)
stale_read  a get serves the PREVIOUS version of the object (lagging
            watch cache / follower read)
watch_drop  a watch event is silently lost (or the stream dies, on the
            HTTP fake) — consumers must recover via resync/relist
========== ==============================================================
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.retry import TransientAPIError

FAULTS = ("write_error", "lost_response", "read_error", "conflict",
          "timeout", "stale_read", "watch_drop")

_WRITE_VERBS = ("create", "update", "update_status", "delete", "patch",
                "put", "post")


@dataclass
class FaultProfile:
    """Per-fault injection rates, seeded. ``overrides`` maps
    ``(verb, kind)`` — either element may be ``"*"`` — to
    ``{fault: rate}``, most-specific match wins; base rates apply
    otherwise."""

    seed: int = 0
    write_error_rate: float = 0.0
    lost_response_rate: float = 0.0
    read_error_rate: float = 0.0
    conflict_rate: float = 0.0
    timeout_rate: float = 0.0
    stale_read_rate: float = 0.0
    watch_drop_rate: float = 0.0
    latency_seconds: float = 0.0
    overrides: Dict[Tuple[str, str], Dict[str, float]] = field(
        default_factory=dict)

    def rate(self, fault: str, verb: str = "*", kind: str = "*") -> float:
        for key in ((verb, kind), (verb, "*"), ("*", kind)):
            o = self.overrides.get(key)
            if o is not None and fault in o:
                return o[fault]
        return getattr(self, f"{fault}_rate", 0.0)

    @classmethod
    def named(cls, name: str, seed: int = 0) -> "FaultProfile":
        """The presets the CLI/bench accept. ``default`` is the
        acceptance-criteria profile: >=5% write errors, >=5% conflicts,
        plus every other fault class at a non-zero rate."""
        if name == "off":
            return cls(seed=seed)
        if name == "default":
            return cls(seed=seed,
                       write_error_rate=0.05, conflict_rate=0.05,
                       read_error_rate=0.02, timeout_rate=0.02,
                       stale_read_rate=0.05, watch_drop_rate=0.05,
                       lost_response_rate=0.01)
        if name == "heavy":
            return cls(seed=seed,
                       write_error_rate=0.15, conflict_rate=0.10,
                       read_error_rate=0.05, timeout_rate=0.05,
                       stale_read_rate=0.10, watch_drop_rate=0.10,
                       lost_response_rate=0.03)
        raise ValueError(f"unknown fault profile {name!r}; "
                         "expected off|default|heavy")


class FaultInjector:
    """Seeded decision engine + per-fault counters. One RNG behind one
    lock: given the same request sequence, the same seed injects the
    same faults (thread interleaving still varies the sequence — the
    seed bounds the search space, it does not promise bit-identical
    schedules)."""

    def __init__(self, profile: FaultProfile):
        self.profile = profile
        self._rng = random.Random(profile.seed)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {f: 0 for f in FAULTS}

    def decide(self, fault: str, verb: str = "*", kind: str = "*") -> bool:
        rate = self.profile.rate(fault, verb, kind)
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.counts[fault] = self.counts.get(fault, 0) + 1
        if hit:
            metrics.chaos_faults_injected.inc(fault=fault)
        return hit

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())


class ChaosStore:
    """Duck-types the ``Store`` surface the controllers consume,
    injecting the profile's faults on the way through. Reads/writes by
    the HARNESS (pollers, fake kubelets) should go to the wrapped
    store directly — the chaos sits between the OPERATOR and its
    apiserver, not inside the world.

    Injection points: CRUD verbs raise ``TransientAPIError`` (5xx),
    ``ConflictError`` (409) or ``TimeoutError``; ``get`` may serve the
    object's previous version (stale read); watch handlers silently
    lose events at the drop rate — consumers must recover via their
    level-triggered resync, which is exactly the contract under test.
    ``project``/``owned_keys``/``count``/``keys`` pass through
    untouched (lock-held hot-path scans; the HTTP analog has no such
    verbs to fault)."""

    def __init__(self, store, profile: Optional[FaultProfile] = None,
                 injector: Optional[FaultInjector] = None):
        self.inner = store
        self.injector = injector or FaultInjector(profile or FaultProfile())
        # (kind, ns, name) -> previous stored version (stale-read pool).
        self._history: Dict[Tuple[str, str, str], object] = {}
        self._hist_lock = threading.Lock()

    # -- fault plumbing --------------------------------------------------

    def _latency(self) -> None:
        d = self.injector.profile.latency_seconds
        if d:
            time.sleep(d)

    def _maybe_read_fault(self, verb: str, kind: str) -> None:
        self._latency()
        if self.injector.decide("timeout", verb, kind):
            raise TimeoutError(f"injected timeout ({verb} {kind})")
        if self.injector.decide("read_error", verb, kind):
            raise TransientAPIError(
                f"injected server error ({verb} {kind})")

    def _maybe_write_fault(self, verb: str, kind: str,
                           conflictable: bool) -> None:
        self._latency()
        if self.injector.decide("timeout", verb, kind):
            raise TimeoutError(f"injected timeout ({verb} {kind})")
        if conflictable and self.injector.decide("conflict", verb, kind):
            raise store_mod.ConflictError(
                f"injected conflict ({verb} {kind})")
        if self.injector.decide("write_error", verb, kind):
            raise TransientAPIError(
                f"injected server error ({verb} {kind})")

    def _after_write(self, verb: str, kind: str, result):
        if self.injector.decide("lost_response", verb, kind):
            raise TransientAPIError(
                f"injected lost response ({verb} {kind}): write applied, "
                "reply dropped")
        return result

    def _remember(self, kind: str, namespace: str, name: str) -> None:
        """Stash the current version before a write, feeding stale
        reads."""
        if self.injector.profile.rate("stale_read") <= 0.0:
            return
        cur = self.inner.try_get(kind, namespace, name)
        if cur is not None:
            with self._hist_lock:
                self._history[(kind, namespace, name)] = cur

    # -- CRUD ------------------------------------------------------------

    def create(self, kind: str, obj):
        self._maybe_write_fault("create", kind, conflictable=False)
        return self._after_write("create", kind,
                                 self.inner.create(kind, obj))

    def get(self, kind: str, namespace: str, name: str):
        self._maybe_read_fault("get", kind)
        if self.injector.decide("stale_read", "get", kind):
            with self._hist_lock:
                stale = self._history.get((kind, namespace, name))
            if stale is not None:
                return stale.deepcopy()
        return self.inner.get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except store_mod.NotFoundError:
            return None

    def get_snapshot(self, kind: str, namespace: str, name: str):
        """Frozen-snapshot read with the same fault surface as get:
        injected timeouts/5xx, and stale reads served from the history
        pool (a historic version is frozen too — the consumer contract
        is identical)."""
        self._maybe_read_fault("get", kind)
        if self.injector.decide("stale_read", "get", kind):
            with self._hist_lock:
                stale = self._history.get((kind, namespace, name))
            if stale is not None:
                return stale
        return self.inner.get_snapshot(kind, namespace, name)

    def list(self, kind: str, namespace=None, selector=None):
        self._maybe_read_fault("list", kind)
        return self.inner.list(kind, namespace=namespace,
                               selector=selector)

    def list_claimable(self, kind: str, namespace: str, selector,
                       owner_uid: str):
        self._maybe_read_fault("list", kind)
        return self.inner.list_claimable(kind, namespace, selector,
                                         owner_uid)

    def update(self, kind: str, obj):
        self._remember(kind, obj.metadata.namespace, obj.metadata.name)
        self._maybe_write_fault("update", kind, conflictable=True)
        return self._after_write("update", kind,
                                 self.inner.update(kind, obj))

    def update_status(self, kind: str, obj):
        self._remember(kind, obj.metadata.namespace, obj.metadata.name)
        self._maybe_write_fault("update_status", kind, conflictable=True)
        return self._after_write("update_status", kind,
                                 self.inner.update_status(kind, obj))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._remember(kind, namespace, name)
        self._maybe_write_fault("delete", kind, conflictable=False)
        self.inner.delete(kind, namespace, name)
        self._after_write("delete", kind, None)

    def try_delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self.delete(kind, namespace, name)
            return True
        except store_mod.NotFoundError:
            return False

    # -- pass-throughs (hot-path scans; no HTTP analog) ------------------

    def project(self, kind: str, fn, namespace=None):
        return self.inner.project(kind, fn, namespace=namespace)

    def owned_keys(self, kind: str, owner_uid: str):
        return self.inner.owned_keys(kind, owner_uid)

    def count(self, kind: str) -> int:
        return self.inner.count(kind)

    def keys(self, kind: str):
        return self.inner.keys(kind)

    def latest_rv(self) -> int:
        return self.inner.latest_rv()

    def list_page(self, kind: str, namespace=None, selector=None,
                  limit=None, after=None):
        self._maybe_read_fault("list", kind)
        return self.inner.list_page(kind, namespace=namespace,
                                    selector=selector, limit=limit,
                                    after=after)

    # -- watch -----------------------------------------------------------

    def watch(self, kind: str, handler, replay: bool = True,
              since_rv=None):
        injector = self.injector

        def chaotic(etype, obj):
            if injector.decide("watch_drop", "watch", kind):
                return  # silently lost on the wire
            handler(etype, obj)

        return self.inner.watch(kind, chaotic, replay=replay,
                                since_rv=since_rv)

    def stop_watchers(self) -> None:
        self.inner.stop_watchers()


def crash_controller(controller, *extras) -> None:
    """Operator crash analog: stop the controller (and any co-located
    subsystems — health, ckpt, binder — passed as ``extras``) so every
    piece of in-memory state dies with it: workqueue backlog,
    expectations, bootstrap-hash caches, barrier deadline anchors,
    drain grace anchors, rebind stopwatches. Python threads cannot be
    killed mid-instruction, so in-flight syncs drain first — the state
    LOSS is the crash analog the invariants care about; the store (the
    durable plane) is untouched. Cold-start a fresh assembly against
    the surviving store afterwards and convergence must hold."""
    for part in (controller, *extras):
        if part is None:
            continue
        try:
            part.stop()
        except Exception:
            pass
