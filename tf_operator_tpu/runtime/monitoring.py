"""Monitoring HTTP endpoint: /metrics + /healthz (+ /debug/*).

Reference parity: startMonitoring (cmd/tf-operator.v1/main.go:39-50)
serves promhttp + net/http/pprof on -monitoring-port (default 8443).
Python profiling is served as a plain-text thread dump at /debug/stacks
instead of pprof. The flight recorder (runtime/trace.py) adds two JSON
surfaces: /debug/traces (retained reconcile traces + phase totals) and
/debug/jobs/<ns>/<name> (the per-job decision journal —
docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tf_operator_tpu.runtime import trace as trace_mod
from tf_operator_tpu.runtime.metrics import REGISTRY, Registry
from tf_operator_tpu.version import version_string

log = logging.getLogger("tpu_operator.monitoring")


def _thread_dump() -> str:
    frames = sys._current_frames()
    out = []
    for t in threading.enumerate():
        out.append(f"--- thread {t.name} (daemon={t.daemon}) ---")
        frame = frames.get(t.ident or -1)
        if frame is not None:
            out.extend(line.rstrip()
                       for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = REGISTRY
    recorder: trace_mod.FlightRecorder = trace_mod.RECORDER
    journal: trace_mod.DecisionJournal = trace_mod.JOURNAL

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = self.registry.render_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body = b"ok\n"
            ctype = "text/plain"
        elif path == "/version":
            body = (json.dumps({"version": version_string()}) + "\n").encode()
            ctype = "application/json"
        elif path == "/debug/stacks":
            body = _thread_dump().encode()
            ctype = "text/plain"
        elif path == "/debug/traces":
            # Served whether or not tracing is on: off = empty recorder
            # (plus whatever was retained before it was turned off).
            payload = {"enabled": trace_mod.enabled(),
                       **self.recorder.snapshot()}
            body = (json.dumps(payload) + "\n").encode()
            ctype = "application/json"
        elif path.startswith("/debug/jobs/"):
            parts = path[len("/debug/jobs/"):].split("/")
            decisions = (self.journal.decisions(parts[0], parts[1])
                         if len(parts) == 2 and all(parts) else None)
            if decisions is None:
                self._send_json(404, {
                    "error": "no decision journal for this job (unknown "
                             "job, or no control-plane decision has "
                             "touched it yet)",
                    "path": path})
                return
            self._send_json(200, {"namespace": parts[0], "name": parts[1],
                                  "decisions": decisions})
            return
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:
        log.debug("http: " + fmt, *args)


class MonitoringServer:
    """Serves the registry on a background thread; port 0 = ephemeral."""

    def __init__(self, port: int = 8443, host: str = "127.0.0.1",
                 registry: Optional[Registry] = None,
                 recorder: Optional[trace_mod.FlightRecorder] = None,
                 journal: Optional[trace_mod.DecisionJournal] = None):
        handler = type("Handler", (_Handler,),
                       {"registry": registry or REGISTRY,
                        "recorder": recorder or trace_mod.RECORDER,
                        "journal": journal or trace_mod.JOURNAL})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="monitoring", daemon=True)
        self._thread.start()
        log.info("monitoring endpoint on :%d (/metrics /healthz "
                 "/debug/traces /debug/jobs/<ns>/<name>)", self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
