"""Rate-limited work queue with K8s workqueue semantics.

Reference dependency: k8s.io/client-go/util/workqueue as used by
job_controller.go:139-142. Semantics preserved:

- De-duplication: an item present in the queue is not added twice.
- In-flight marking: an item re-added while being processed is deferred
  until ``done`` and then requeued (level-triggered, same-key serialized —
  this is the engine's only concurrency-safety requirement).
- ``add_rate_limited`` applies per-item exponential backoff;
  ``num_requeues`` feeds the engine's BackoffLimit policy;
  ``forget`` resets the counter.
- ``add_after`` schedules a delayed add (used for ActiveDeadlineSeconds
  re-sync, reference status.go:84-92).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Dict, Hashable, List, Optional, Tuple


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 30.0):
        self._lock = threading.Condition()
        self._queue: deque = deque()
        self._dirty: set = set()
        self._processing: set = set()
        self._failures: Dict[Hashable, int] = {}
        self._delayed: List[Tuple[float, int, Hashable]] = []  # heap
        self._seq = 0
        self._shutting_down = False
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._delay_thread = threading.Thread(target=self._delay_loop,
                                              daemon=True)
        self._delay_thread.start()

    # -- core queue -------------------------------------------------------

    def add(self, item: Hashable) -> None:
        with self._lock:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._lock.notify()

    def get(self, timeout: Optional[float] = None) -> Hashable:
        """Block until an item is available. Raises ShutDown when drained
        after shutdown, or TimeoutError on timeout."""
        with self._lock:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError()
                self._lock.wait(remaining)
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Hashable) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._lock.notify()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._lock:
            self._shutting_down = True
            self._lock.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._lock:
            return self._shutting_down

    # -- rate limiting ----------------------------------------------------

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def add_rate_limited(self, item: Hashable) -> None:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        delay = min(self._base_delay * (2 ** n), self._max_delay)
        self.add_after(item, delay)

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._lock:
            if self._shutting_down:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay,
                                           self._seq, item))
            self._lock.notify_all()

    def _delay_loop(self) -> None:
        while True:
            with self._lock:
                if self._shutting_down and not self._delayed:
                    return
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, item = heapq.heappop(self._delayed)
                    if item not in self._dirty:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._queue.append(item)
                            self._lock.notify()
                wait = 0.2
                if self._delayed:
                    wait = min(wait, max(0.0, self._delayed[0][0] - now))
                self._lock.wait(wait)
