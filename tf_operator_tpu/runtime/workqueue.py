"""Rate-limited work queue with K8s workqueue semantics.

Reference dependency: k8s.io/client-go/util/workqueue as used by
job_controller.go:139-142. Semantics preserved:

- De-duplication: an item present in the queue is not added twice
  (coalescing — a 256-pod gang start collapses its event storm into
  one pending sync per job; counted by ``workqueue_coalesced_total``).
- In-flight marking: an item re-added while being processed is deferred
  until ``done`` and then requeued (level-triggered, same-key serialized —
  this is the engine's only concurrency-safety requirement, and what
  makes ``threadiness > 1`` safe: two workers can never hold the same
  key simultaneously).
- ``add_rate_limited`` applies per-item exponential backoff;
  ``num_requeues`` feeds the engine's BackoffLimit policy;
  ``forget`` resets the counter.
- ``add_after`` schedules a delayed add (used for ActiveDeadlineSeconds
  and TTL re-sync, reference status.go:84-92, job.go:345-357).

Wakeups: ``get()`` waiters and the delay loop wait on SEPARATE
conditions sharing one mutex. They used to share a single condition,
and ``add``'s lone ``notify()`` could wake the delay loop instead of a
``get()`` waiter — the freshly queued item then sat until a worker's
poll timeout (~0.5 s of sync latency per quiet-period add; masked by
event churn, exposed by the elastic resize pass's steady-state grows).
``notify_all`` is not the fix either: waking every worker and the
delay loop on every add is a thundering herd that starves the
process's other threads (watch streams, servers) under event storms.

Observability lives HERE, under the queue's own lock (the depth gauge
used to be set racily at the two controller call sites):
``workqueue_depth`` on every transition, ``workqueue_latency_seconds``
(add -> get wait) on every pop, ``workqueue_coalesced_total`` on every
deduplicated add.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Dict, Hashable, List, Optional, Tuple

from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import trace as trace_mod


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 30.0,
                 instrument: bool = True):
        # One mutex, two wait channels: ``_items`` for get() waiters,
        # ``_delay_cv`` for the delay loop — a ready-item notify can
        # only ever wake a consumer (see module docstring).
        self._mutex = threading.Lock()
        self._items = threading.Condition(self._mutex)
        self._delay_cv = threading.Condition(self._mutex)
        self._queue: deque = deque()
        self._dirty: set = set()
        self._processing: set = set()
        self._failures: Dict[Hashable, int] = {}
        self._added_at: Dict[Hashable, float] = {}
        self._delayed: List[Tuple[float, int, Hashable]] = []  # heap
        self._seq = 0
        self._shutting_down = False
        self._base_delay = base_delay
        self._max_delay = max_delay
        # Process-global metrics; tests that build throwaway queues can
        # opt out so they don't scribble on the operator's gauges.
        self._instrument = instrument
        self._delay_thread = threading.Thread(target=self._delay_loop,
                                              daemon=True)
        self._delay_thread.start()

    # -- instrumentation (callers hold self._mutex) ------------------------

    def _mark_queued(self, item: Hashable) -> None:
        self._queue.append(item)
        self._added_at.setdefault(item, time.monotonic())
        self._set_depth()

    def _set_depth(self) -> None:
        if self._instrument:
            metrics.workqueue_depth.set(len(self._queue))

    def _coalesced(self) -> None:
        if self._instrument:
            metrics.workqueue_coalesced.inc()

    # -- core queue -------------------------------------------------------

    def add(self, item: Hashable) -> None:
        with self._items:
            if self._shutting_down:
                return
            if item in self._dirty:
                self._coalesced()
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._mark_queued(item)
            self._items.notify()

    def get(self, timeout: Optional[float] = None) -> Hashable:
        """Block until an item is available. Raises ShutDown when drained
        after shutdown, or TimeoutError on timeout."""
        with self._items:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError()
                self._items.wait(remaining)
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            added = self._added_at.pop(item, None)
            if added is not None and self._instrument:
                wait = time.monotonic() - added
                metrics.workqueue_latency_seconds.observe(wait)
                # Flight-recorder phase attribution: enqueue->dequeue
                # wait is the "queue_wait" phase of the item's next
                # sync (no span — the wait belongs to no trace yet).
                trace_mod.note_phase("queue_wait", wait)
            self._set_depth()
            return item

    def done(self, item: Hashable) -> None:
        with self._items:
            self._processing.discard(item)
            if item in self._dirty:
                self._mark_queued(item)
                self._items.notify()

    def __len__(self) -> int:
        with self._mutex:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._mutex:
            self._shutting_down = True
            self._items.notify_all()
            self._delay_cv.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._mutex:
            return self._shutting_down

    # -- rate limiting ----------------------------------------------------

    def num_requeues(self, item: Hashable) -> int:
        with self._mutex:
            return self._failures.get(item, 0)

    def forget(self, item: Hashable) -> None:
        with self._mutex:
            self._failures.pop(item, None)

    def add_rate_limited(self, item: Hashable) -> None:
        with self._mutex:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        delay = min(self._base_delay * (2 ** n), self._max_delay)
        self.add_after(item, delay)

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._delay_cv:
            if self._shutting_down:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay,
                                           self._seq, item))
            self._delay_cv.notify()  # re-arm the loop's wait window

    def _delay_loop(self) -> None:
        while True:
            with self._delay_cv:
                if self._shutting_down and not self._delayed:
                    return
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, item = heapq.heappop(self._delayed)
                    if item not in self._dirty:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._mark_queued(item)
                            self._items.notify()
                    else:
                        self._coalesced()
                wait = 0.2
                if self._delayed:
                    wait = min(wait, max(0.0, self._delayed[0][0] - now))
                self._delay_cv.wait(wait)
