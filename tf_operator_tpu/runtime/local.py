"""Local process backend — the kubelet/data-plane analog.

The reference hands pods to kubelet and watches status flow back through
the API server (SURVEY §3.3). This backend does the same hermetically:
it watches the store for pods, runs each container as a subprocess, and
writes phase transitions (Pending -> Running -> Succeeded/Failed with
exit codes) back to the store, driving the controller's watch feedback
loop. Pod-level restartPolicy (Always/OnFailure) is honored in-place with
restart counts, which feeds the engine's PastBackoffLimit policy.

Service discovery is pluggable: env rendered by the bootstrap layer uses
cluster DNS names; the ``resolver`` rewrites them to reachable addresses
at spawn time. The default ``LoopbackEnvResolver`` maps everything to
127.0.0.1 with a per-job coordinator port (hermetic single-host runs);
node agents use ``agent.ControlPlaneEnvResolver``, which resolves names
through pod placement records in the served control plane (kube-dns
analog). A ``pod_filter`` scopes the backend to pods bound to one node.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from tf_operator_tpu.api.types import (
    ContainerStatus,
    Pod,
    PodPhase,
    PodStatus,
    RestartPolicy,
)
from tf_operator_tpu.runtime import relay as relay_mod
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.store import ADDED, DELETED, MODIFIED, Store

log = logging.getLogger("tpu_operator.local_backend")

_GRACE_SECONDS = 3.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class _RunningPod:
    pod: Pod
    processes: Dict[str, subprocess.Popen] = field(default_factory=dict)
    restart_counts: Dict[str, int] = field(default_factory=dict)
    stop_requested: bool = False
    done: bool = False
    # True while this pod's processes are counted in the backend's
    # gang-occupancy registry (see _gang_acquire/_gang_release).
    gang_held: bool = False
    # Last preemption-notice payload forwarded to the worker process
    # (dedup: each barrier's notice is written to the file once).
    notice_written: str = ""
    # mtime (ns) of the worker's checkpoint file at the last mirror into
    # the store's CheckpointRecord.
    ckpt_mtime: int = 0


class LoopbackEnvResolver:
    """Single-host resolution: rewrite cluster DNS names to 127.0.0.1
    with one free coordinator port per job. The hermetic default; served
    deployments use the agent's control-plane resolver instead."""

    def __init__(self):
        self._lock = threading.Lock()
        self._job_ports: Dict[str, int] = {}  # job uid -> coord port
        self._host_ports: Dict[str, int] = {}  # cluster DNS name -> port

    def _port_for_host(self, host: str) -> int:
        """Stable loopback port per cluster DNS name, shared by every
        pod this backend spawns — the ps replica binds the SAME port
        its peers dial (single-host kube-dns analog)."""
        with self._lock:
            port = self._host_ports.get(host)
            if port is None:
                port = _free_port()
                self._host_ports[host] = port
            return port

    def _rewrite_cluster_spec(self, raw: str) -> str:
        """Rewrite ONLY the ps entries: they are the addresses tasks
        actually dial through the cluster spec (train/ps.py). Other
        roles' entries stay DNS-named — they are identity, part of the
        golden bootstrap contract (test_runconfig_golden_full_topology),
        and their traffic (jax coordinator) is resolved separately."""
        import json

        try:
            spec = json.loads(raw)
        except ValueError:
            return raw
        cluster = spec.get("cluster") or {}
        if cluster.get("ps"):
            cluster["ps"] = [
                f"127.0.0.1:{self._port_for_host(h.rsplit(':', 1)[0])}"
                for h in cluster["ps"]]
            spec["cluster"] = cluster
            return json.dumps(spec, sort_keys=True)
        return raw

    def resolve(self, pod: Pod, env: Dict[str, str]) -> Dict[str, str]:
        job_uid = ""
        ref = pod.metadata.controller_ref()
        if ref is not None:
            job_uid = ref.uid
        with self._lock:
            port = self._job_ports.get(job_uid)
            if port is None:
                port = _free_port()
                self._job_ports[job_uid] = port
        out = {}
        for k, v in env.items():
            if k in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
                out[k] = f"127.0.0.1:{port}"
            elif k == "TPU_WORKER_HOSTNAMES":
                out[k] = ",".join("127.0.0.1" for _ in v.split(","))
            elif k == "TPUJOB_CLUSTER_SPEC":
                # PS/worker tasks dial each other through the cluster
                # spec; rewrite its DNS names to stable loopback ports.
                out[k] = self._rewrite_cluster_spec(v)
            else:
                out[k] = v
        return out


class LocalProcessBackend:
    def __init__(self, store: Store, workdir: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 resolver=None,
                 pod_filter=None):
        self.store = store
        self.workdir = workdir or os.getcwd()
        self.extra_env = dict(extra_env or {})
        # Service-discovery strategy: rewrites bootstrap env (coordinator
        # address, worker hostnames) to reachable addresses at spawn time.
        self.resolver = resolver or LoopbackEnvResolver()
        # Which pods this backend runs (a node agent passes "pods bound
        # to me"); None = every pod in the store.
        self.pod_filter = pod_filter
        # Pod stdout/stderr capture (kubelet container-log analog);
        # surfaced to clients via pod.status.log_path.
        self.log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), f"tpujob-logs-{os.getpid()}")
        self._lock = threading.Lock()
        self._running: Dict[str, _RunningPod] = {}  # "ns/name" -> state
        self._watcher = None
        self._stopped = False
        # Gang groups with LIVE local processes: (ns, group) -> chips
        # held (sum of the spawned pods' google.com/tpu requests).
        # Registered synchronously at spawn and released only after
        # process exit, so the gang scheduler's draining_provider sees
        # the chips as occupied through the whole process lifetime —
        # including the termination-grace window after the store pod
        # (or even the whole SliceGroup, on job deletion) is already
        # gone (round-4 Weak #6: the store delete alone opened an
        # up-to-_GRACE_SECONDS overlap where a successor could run
        # alongside dying victims). Value = [pod count, chips] so the
        # scheduler can both gate occupancy and keep budget booked for
        # groups that no longer exist.
        self._gang_procs: Dict[tuple, list] = {}
        # Called (if set) when a gang group's last dying process exits,
        # so admission re-runs immediately instead of at the next
        # resync (process exit writes no store event to ride).
        self.on_gang_drained = None

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._watcher = self.store.watch(store_mod.PODS, self._on_pod_event)

    def stop(self) -> None:
        self._stopped = True
        if self._watcher:
            self._watcher.stop()
        with self._lock:
            running = list(self._running.values())
        for rp in running:
            self._terminate(rp)

    def _on_pod_event(self, event_type: str, pod: Pod) -> None:
        if self._stopped:
            return
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        # MODIFIED starts pods too: a node agent's claim (binding
        # spec.node_name) arrives as MODIFIED, and the _running dedup
        # makes re-delivery harmless.
        if event_type in (ADDED, MODIFIED):
            if self.pod_filter is not None and not self.pod_filter(pod):
                return
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                return  # terminal status echoes (incl. our own writes)
            with self._lock:
                running = self._running.get(key)
                if running is None:
                    rp = _RunningPod(pod=pod)
                    self._running[key] = rp
            if running is not None:
                # Already running here: the only update the data plane
                # acts on is a preemption notice landing on the pod —
                # forward it to the worker process as a file
                # (controller/ckpt.py save-before-evict barrier).
                self._forward_notice(running, pod)
                return
            threading.Thread(target=self._run_pod, args=(key, rp),
                             daemon=True).start()
        elif event_type == DELETED:
            with self._lock:
                rp = self._running.pop(key, None)
            if rp is not None:
                # Termination can block for the grace period; keep the watch
                # dispatcher thread free.
                threading.Thread(target=self._terminate, args=(rp,),
                                 daemon=True).start()
            # Log retention follows the pod object (kubelet semantics);
            # the checkpoint-coordination sidecar files follow it too.
            try:
                os.unlink(self.pod_log_path(pod))
            except OSError:
                pass
            relay_mod.cleanup(self.log_dir, pod)

    # ------------------------------------------------------------------

    def _run_pod(self, key: str, rp: _RunningPod) -> None:
        pod = rp.pod
        if not self._await_gang_admission(rp):
            return  # pod deleted while gated
        if rp.stop_requested:
            return  # deleted between admission and spawn
        try:
            self._spawn_all(rp)
        except Exception as e:  # bad command etc. -> Failed
            log.warning("pod %s failed to start: %s", key, e)
            self._write_status(pod, PodPhase.FAILED, message=str(e))
            return
        # Chips are held from the first spawned process until the last
        # one EXITS (not until the store pod is deleted) — the gang
        # scheduler reads this registry to close the preemption
        # overlap window.
        self._gang_acquire(rp)
        if rp.stop_requested:
            # Deletion raced the spawn: _terminate saw an empty process
            # map, so these processes would otherwise leak.
            self._terminate(rp)
            return
        self._write_running(rp)
        self._wait_pod(key, rp)

    def _await_gang_admission(self, rp: _RunningPod) -> bool:
        """Gang-scheduled pods stay Pending until their SliceGroup is
        admitted (Volcano's gating behavior). Gated on the gang annotation,
        which is stamped on every pod of a gang-scheduled job regardless of
        any custom scheduler name in the template."""
        from tf_operator_tpu.api import constants
        from tf_operator_tpu.controller.gang import PHASE_INQUEUE, PHASE_RUNNING

        pod = rp.pod
        group_name = pod.metadata.annotations.get(
            constants.ANNOTATION_GANG_GROUP, "")
        if not group_name:
            return True
        while not (rp.stop_requested or self._stopped):
            group = self.store.try_get(store_mod.SLICEGROUPS,
                                       pod.metadata.namespace, group_name)
            if group is not None and group.status.phase in (PHASE_INQUEUE,
                                                            PHASE_RUNNING):
                # Mark-then-recheck, not check-then-mark: persist the
                # release FIRST, then confirm the group is still
                # admitted. A preemption between our phase read and the
                # marker write would otherwise see no occupying pod and
                # hand these chips to the preemptor while we spawn.
                self._mark_released(pod, True)
                group = self.store.try_get(store_mod.SLICEGROUPS,
                                           pod.metadata.namespace,
                                           group_name)
                if group is not None and group.status.phase in (
                        PHASE_INQUEUE, PHASE_RUNNING):
                    return True
                self._mark_released(pod, False)  # lost the race: re-gate
                continue
            time.sleep(0.05)
        return False

    def _mark_released(self, pod: Pod, released: bool) -> None:
        """Persist gang_released BEFORE spawning, so the gang scheduler
        counts this pod as occupying chips through the whole spawn
        window — a preemption landing mid-spawn evicts it instead of
        double-booking its chips (see PodStatus.gang_released)."""
        stored = self.store.try_get(store_mod.PODS, pod.metadata.namespace,
                                    pod.metadata.name)
        if stored is None:
            return
        stored.status.gang_released = released
        pod.status.gang_released = released
        try:
            self.store.update_status(store_mod.PODS, stored)
        except store_mod.NotFoundError:
            pass

    def _spawn_all(self, rp: _RunningPod) -> None:
        for container in rp.pod.spec.containers:
            self._spawn(rp, container.name)

    def _spawn(self, rp: _RunningPod, container_name: str) -> None:
        pod = rp.pod
        container = pod.spec.container(container_name)
        argv = list(container.command) + list(container.args)
        if not argv:
            raise ValueError(f"container {container_name} has no command")
        env = dict(self.extra_env)
        env.setdefault("PATH", os.environ.get("PATH", "/usr/bin:/bin"))
        for var in ("PYTHONPATH", "HOME", "LANG"):
            if var in os.environ:
                env.setdefault(var, os.environ[var])
        env.update(self.resolver.resolve(pod, container.env))
        env["TPUJOB_POD_NAME"] = pod.metadata.name
        env["TPUJOB_POD_NAMESPACE"] = pod.metadata.namespace
        # Checkpoint-coordination handoff (controller/ckpt.py): where a
        # preemption notice will appear, and where the worker publishes
        # its checkpoint state for the plane to mirror into its
        # CheckpointRecord (train/checkpoint.py CheckpointHook reads /
        # writes these).
        from tf_operator_tpu.api import constants as _c

        env[_c.ENV_PREEMPT_FILE] = self.pod_preempt_path(pod)
        env[_c.ENV_CKPT_FILE] = self.pod_ckpt_path(pod)
        os.makedirs(self.log_dir, exist_ok=True)
        log_path = self.pod_log_path(pod)
        with open(log_path, "ab") as log_file:
            proc = subprocess.Popen(
                argv,
                cwd=container.working_dir or self.workdir,
                env=env,
                stdout=log_file,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        rp.processes[container_name] = proc

    def pod_log_path(self, pod: Pod) -> str:
        # Keyed by uid so a restart-with-identity (same name, new pod)
        # gets a fresh file, not the dead incarnation's output.
        uid = (pod.metadata.uid or "nouid")[:8]
        return os.path.join(
            self.log_dir,
            f"{pod.metadata.namespace}.{pod.metadata.name}.{uid}.log")

    def pod_preempt_path(self, pod: Pod) -> str:
        """Where this pod's worker process finds a preemption notice
        (incarnation-keyed like the log: a recreated pod must never read
        the dead incarnation's notice and 'ack' a barrier it never saved
        under). Path derivation is shared with the kube node agent
        (runtime/relay.py)."""
        return relay_mod.preempt_path(self.log_dir, pod)

    def pod_ckpt_path(self, pod: Pod) -> str:
        """Where this pod's worker process publishes checkpoint state
        (saves / barrier acks / restore confirmation) for the plane to
        mirror into its CheckpointRecord."""
        return relay_mod.ckpt_path(self.log_dir, pod)

    def _forward_notice(self, rp: _RunningPod, pod: Pod) -> None:
        """Write the pod's preemption-notice annotation to the worker's
        notice file (atomic publish; the training loop polls it each
        step). The kubelet analog of the coordinator's annotation stamp
        reaching the container."""
        from tf_operator_tpu.api import constants as _c

        notice = pod.metadata.annotations.get(
            _c.ANNOTATION_PREEMPT_NOTICE, "")
        try:
            rp.notice_written = relay_mod.forward_notice(
                self.log_dir, rp.pod, notice, rp.notice_written)
        except OSError:
            return  # next MODIFIED/poll retries

    def _mirror_ckpt_record(self, rp: _RunningPod) -> None:
        """Mirror the worker's checkpoint file into its CheckpointRecord
        — the data plane reports checkpoint state exactly like it
        reports pod phase (controller/ckpt.py reads the records to run
        barriers and derive restore steps). A partially-written or
        unparseable file is skipped; the next tick retries."""
        pod = rp.pod
        data, rp.ckpt_mtime = relay_mod.read_ckpt_file(
            self.pod_ckpt_path(pod), rp.ckpt_mtime)
        if data is None:
            return
        try:
            if not relay_mod.upsert_checkpoint_record(
                    self.store, pod, data, _now()):
                rp.ckpt_mtime = 0  # lost a race; next tick re-mirrors
        except Exception:
            log.debug("checkpoint record mirror failed", exc_info=True)
            rp.ckpt_mtime = 0

    # ------------------------------------------------------------------

    def _wait_pod(self, key: str, rp: _RunningPod) -> None:
        """Monitor processes; honor pod restartPolicy; write final phase."""
        pod = rp.pod
        policy = pod.spec.restart_policy or RestartPolicy.NEVER
        # A notice stamped while the pod was gate-held arrives with no
        # further MODIFIED event; forward it now that processes exist.
        self._forward_notice(rp, pod)
        while True:
            if rp.stop_requested:
                return
            self._mirror_ckpt_record(rp)
            exited = {}
            for name, proc in list(rp.processes.items()):
                code = proc.poll()
                if code is not None:
                    exited[name] = code
            if len(exited) == len(rp.processes):
                # all containers done; decide restart vs terminal
                should_restart = (
                    policy == RestartPolicy.ALWAYS
                    or (policy == RestartPolicy.ON_FAILURE
                        and any(c != 0 for c in exited.values())))
                if should_restart and not rp.stop_requested:
                    for name in exited:
                        rp.restart_counts[name] = rp.restart_counts.get(name, 0) + 1
                    try:
                        self._spawn_all(rp)
                    except Exception as e:
                        # All processes are dead: the chips must not
                        # stay booked behind a failed respawn.
                        self._gang_release(rp)
                        self._write_status(pod, PodPhase.FAILED, message=str(e))
                        return
                    self._write_running(rp)
                    continue
                rp.done = True
                phase = (PodPhase.SUCCEEDED
                         if all(c == 0 for c in exited.values())
                         else PodPhase.FAILED)
                # Final mirror: a save completing in the process's last
                # instants (barrier ack, then exit) must not be lost.
                self._mirror_ckpt_record(rp)
                self._gang_release(rp)  # natural death frees the chips
                self._write_status(pod, phase, exit_codes=exited, rp=rp)
                return
            time.sleep(0.02)

    def draining_gang_groups(self) -> Dict[tuple, Dict[str, int]]:
        """(namespace, gang group) -> {"pods": live-process pod count,
        "chips": chips those pods hold}. Consumed by the gang
        scheduler's draining_provider so freed chips only admit a
        successor after the previous holders actually exited — even
        when the holder's SliceGroup itself was deleted with its job
        (pods gates occupancy; chips keeps deleted groups' budget
        booked)."""
        with self._lock:
            return {k: {"pods": v[0], "chips": v[1]}
                    for k, v in self._gang_procs.items()}

    def _gang_key(self, pod: Pod):
        from tf_operator_tpu.api import constants

        group = pod.metadata.annotations.get(
            constants.ANNOTATION_GANG_GROUP, "")
        return (pod.metadata.namespace, group) if group else None

    @staticmethod
    def _pod_chips(pod: Pod) -> int:
        from tf_operator_tpu.controller.binder import pod_chip_demand

        return pod_chip_demand(pod)

    def _gang_acquire(self, rp: _RunningPod) -> None:
        key = self._gang_key(rp.pod)
        if key is None:
            return
        with self._lock:
            if rp.gang_held:
                return
            rp.gang_held = True
            entry = self._gang_procs.setdefault(key, [0, 0])
            entry[0] += 1
            entry[1] += self._pod_chips(rp.pod)

    def _gang_release(self, rp: _RunningPod) -> None:
        key = self._gang_key(rp.pod)
        if key is None:
            return
        with self._lock:
            if not rp.gang_held:
                return
            rp.gang_held = False
            entry = self._gang_procs.get(key, [1, 0])
            entry[0] -= 1
            entry[1] = max(0, entry[1] - self._pod_chips(rp.pod))
            left = entry[0]
            if left <= 0:
                self._gang_procs.pop(key, None)
        if left <= 0 and self.on_gang_drained is not None:
            # Process exit writes no store event; poke admission so the
            # waiting successor lands now, not at the next resync.
            try:
                self.on_gang_drained()
            except Exception:
                log.debug("on_gang_drained failed", exc_info=True)

    def _terminate(self, rp: _RunningPod) -> None:
        rp.stop_requested = True
        try:
            procs = list(rp.processes.values())
            for proc in procs:
                if proc.poll() is None:
                    try:
                        os.killpg(proc.pid, signal.SIGTERM)
                    except (ProcessLookupError, PermissionError):
                        pass
            deadline = time.monotonic() + _GRACE_SECONDS
            for proc in procs:
                remaining = deadline - time.monotonic()
                try:
                    proc.wait(timeout=max(0.05, remaining))
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                    proc.wait(timeout=5)
        finally:
            self._gang_release(rp)

    # ------------------------------------------------------------------

    def _write_running(self, rp: _RunningPod) -> None:
        pod = rp.pod
        status = PodStatus(
            phase=PodPhase.RUNNING,
            start_time=rp.pod.status.start_time or _now(),
            host="127.0.0.1",
            container_statuses=[
                ContainerStatus(name=name, state="Running",
                                restart_count=rp.restart_counts.get(name, 0))
                for name in rp.processes
            ],
        )
        rp.pod.status = status
        self._write_pod_status(pod, status)

    def _write_status(self, pod: Pod, phase: str,
                      exit_codes: Optional[Dict[str, int]] = None,
                      message: str = "",
                      rp: Optional[_RunningPod] = None) -> None:
        statuses = []
        for name, code in (exit_codes or {}).items():
            statuses.append(ContainerStatus(
                name=name, state="Terminated", exit_code=code,
                restart_count=(rp.restart_counts.get(name, 0) if rp else 0)))
        status = PodStatus(phase=phase, message=message,
                           start_time=pod.status.start_time or _now(),
                           host="127.0.0.1",
                           container_statuses=statuses)
        self._write_pod_status(pod, status)

    def _write_pod_status(self, pod: Pod, status: PodStatus) -> None:
        stored = self.store.try_get(store_mod.PODS, pod.metadata.namespace,
                                    pod.metadata.name)
        if stored is None:
            return  # deleted concurrently
        log_path = self.pod_log_path(pod)
        if os.path.exists(log_path):
            status.log_path = log_path
        # Preserve the placement the claiming agent published — peers
        # resolve coordinator addresses from these fields.
        if stored.status.host:
            status.host = stored.status.host
        if stored.status.ports:
            status.ports = dict(stored.status.ports)
        stored.status = status
        try:
            self.store.update_status(store_mod.PODS, stored)
        except store_mod.NotFoundError:
            pass


def _now():
    import datetime as _dt

    return _dt.datetime.now(_dt.timezone.utc)
