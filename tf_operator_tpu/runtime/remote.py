"""RemoteStore: the Store interface over the served API.

The reference's clientsets speak to a remote API server from any process
(app/server.go:198-229; the SDK from anywhere,
api/tf_job_client.py:55-100). RemoteStore is that client: it duck-types
the in-process Store (create/get/list/update/update_status/delete/watch
and friends), so the SDK, node agents, and the engine's controls run
unchanged against an operator in another process or on another host.

Watch is a streaming GET of JSON lines. The watcher tracks the highest
resourceVersion seen on the stream; on connection loss it reconnects
with ``?resourceVersion=<last seen>`` and the server replays only the
missed events from its watch log — no full ADDED storm. Only when the
resume point has been evicted from the log does the server fall back to
the informer relist contract (current objects replayed as ADDED), which
every consumer in this codebase already treats as idempotent.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from tf_operator_tpu.runtime import retry as retry_mod
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.apiserver import WIRE_KINDS

log = logging.getLogger("tpu_operator.remote")

# Verbs safe to replay after an ambiguous failure: GET trivially;
# PUT carries the object's resourceVersion (a replay after a landed
# write loses the CAS -> ConflictError, which every caller handles);
# DELETE replays to NotFound (level-triggered deletes handle it).
# POST (create) is NOT replayed — a landed-then-lost create would
# surface as a spurious AlreadyExists on objects the caller owns.
_IDEMPOTENT_METHODS = ("GET", "PUT", "DELETE")

_RECONNECT_DELAY = 0.5


def _ssl_context(base_url: str, ca_file: Optional[str],
                 insecure_skip_verify: bool) -> Optional[ssl.SSLContext]:
    if not base_url.startswith("https"):
        return None
    ctx = ssl.create_default_context(cafile=ca_file)
    if insecure_skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


def _authed(url: str, token: Optional[str],
            data: Optional[bytes] = None, method: Optional[str] = None,
            headers: Optional[Dict[str, str]] = None
            ) -> urllib.request.Request:
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=dict(headers or {}))
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return req


class RemoteWatcher:
    """Store.Watcher analog over a streaming HTTP connection."""

    def __init__(self, base_url: str, kind: str,
                 handler: Callable[[str, object], None],
                 namespace: Optional[str] = None,
                 token: Optional[str] = None,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 since_rv: Optional[int] = None):
        self._base = f"{base_url}/apis/v1/watch/{kind}"
        self._namespace = namespace
        # Highest resourceVersion seen on the stream; a reconnect
        # resumes from it via the server's watch log, so a dropped
        # connection no longer triggers a full ADDED replay.
        self.last_rv: Optional[int] = since_rv
        self.kind = kind
        self.handler = handler
        self._token = token
        self._ssl = ssl_context
        self._stopped = threading.Event()
        self._resp = None
        self._lock = threading.Lock()
        self.thread = threading.Thread(target=self._loop,
                                       name=f"watch-{kind}", daemon=True)
        self.thread.start()

    def _watch_url(self) -> str:
        params = {}
        if self._namespace is not None:
            params["namespace"] = self._namespace
        if self.last_rv is not None:
            params["resourceVersion"] = str(self.last_rv)
        if not params:
            return self._base
        return self._base + "?" + urllib.parse.urlencode(params)

    def _loop(self) -> None:
        cls = WIRE_KINDS[self.kind]
        auth_failures = 0
        while not self._stopped.is_set():
            try:
                try:
                    resp = urllib.request.urlopen(
                        _authed(self._watch_url(), self._token),
                        context=self._ssl)
                except urllib.error.HTTPError as e:
                    if e.code in (401, 403):
                        # NOT a transient blip: a misconfigured token
                        # never fixes itself — surface loudly and back
                        # off hard so the caller's silent handler is
                        # explicable from the logs.
                        auth_failures += 1
                        if auth_failures == 1 or auth_failures % 60 == 0:
                            log.warning(
                                "watch %s rejected with %d (%s): check "
                                "the bearer token/role; retrying",
                                self.kind, e.code, e.reason)
                        self._stopped.wait(5.0)
                        continue
                    raise
                auth_failures = 0
                with self._lock:
                    if self._stopped.is_set():
                        resp.close()
                        return
                    self._resp = resp
                for raw in resp:
                    if self._stopped.is_set():
                        return
                    raw = raw.strip()
                    if not raw:
                        continue  # keepalive
                    evt = json.loads(raw)
                    obj = cls.from_dict(evt["object"])
                    rv = obj.metadata.resource_version
                    if rv and (self.last_rv is None or rv > self.last_rv):
                        self.last_rv = rv
                    try:
                        self.handler(evt["type"], obj)
                    except Exception:
                        log.exception("watch handler error for %s", self.kind)
            except (OSError, urllib.error.URLError, ValueError,
                    AttributeError):
                # AttributeError: stop() closed the response from another
                # thread mid-read; http.client's internals race their own
                # teardown. Treat like any disconnect.
                if self._stopped.is_set():
                    return
                log.debug("watch %s disconnected; reconnecting", self.kind)
            finally:
                with self._lock:
                    if self._resp is not None:
                        try:
                            self._resp.close()
                        except Exception:
                            pass
                        self._resp = None
            self._stopped.wait(_RECONNECT_DELAY)

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            if self._resp is not None:
                try:
                    # Closing the socket unblocks the reader thread.
                    self._resp.close()
                except Exception:
                    pass
        self.thread.join(timeout=5)


class RemoteStore:
    """HTTP(S) client with the Store's surface. ``token`` rides every
    request as a bearer credential; ``ca_file`` verifies a self-signed
    server (``insecure_skip_verify`` disables verification — test/dev
    only, the kubeconfig insecure-skip-tls-verify analog)."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self._ssl = _ssl_context(self.base_url, ca_file,
                                 insecure_skip_verify)
        self._watchers: List[RemoteWatcher] = []
        self._lock = threading.Lock()

    # -- plumbing ----------------------------------------------------------

    def _open(self, url: str, timeout: Optional[float],
              data: Optional[bytes] = None,
              method: Optional[str] = None,
              headers: Optional[Dict[str, str]] = None):
        return urllib.request.urlopen(
            _authed(url, self.token, data=data, method=method,
                    headers=headers),
            timeout=timeout, context=self._ssl)

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None,
                 query: Optional[Dict[str, str]] = None) -> dict:
        url = f"{self.base_url}{path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"

        def once() -> dict:
            try:
                with self._open(url, self.timeout, data=data,
                                method=method, headers=headers) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                payload = {}
                try:
                    payload = json.loads(e.read() or b"{}")
                except (ValueError, OSError):
                    pass
                reason = payload.get("reason", "")
                message = payload.get("message", str(e))
                if reason == "NotFound":
                    raise store_mod.NotFoundError(message)
                if reason == "AlreadyExists":
                    raise store_mod.AlreadyExistsError(message)
                if reason == "Conflict":
                    raise store_mod.ConflictError(message)
                if e.code == 429 or e.code >= 500:
                    # Server blip/throttle: retryable (classified via
                    # the shared transient taxonomy, runtime/retry.py).
                    raise retry_mod.TransientAPIError(
                        f"API error {e.code}: {message}", code=e.code)
                raise RuntimeError(f"API error {e.code}: {message}")

        if method in _IDEMPOTENT_METHODS:
            # 5xx bursts, timeouts and dropped connections retry in
            # place with capped-jittered backoff instead of surfacing
            # straight to the SDK/agent caller; the scattered ad-hoc
            # "except Exception: sleep and hope" sites this replaces
            # never distinguished transient from semantic failures.
            return retry_mod.with_retries(
                once, policy=retry_mod.CLIENT_POLICY,
                component="remote")
        return once()

    @staticmethod
    def _cls(kind: str):
        cls = WIRE_KINDS.get(kind)
        if cls is None:
            raise KeyError(f"unknown kind {kind!r}")
        return cls

    # -- CRUD (Store surface) ---------------------------------------------

    def create(self, kind: str, obj) -> object:
        data = self._request("POST", f"/apis/v1/{kind}", body=obj.to_dict())
        return self._cls(kind).from_dict(data)

    def get(self, kind: str, namespace: str, name: str) -> object:
        data = self._request("GET", f"/apis/v1/{kind}/{namespace}/{name}")
        return self._cls(kind).from_dict(data)

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except store_mod.NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[object]:
        query: Dict[str, str] = {}
        if namespace is not None:
            query["namespace"] = namespace
        if selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(selector.items()))
        data = self._request("GET", f"/apis/v1/{kind}", query=query)
        cls = self._cls(kind)
        return [cls.from_dict(item) for item in data.get("items", [])]

    def list_page(self, kind: str, namespace: Optional[str] = None,
                  selector: Optional[Dict[str, str]] = None,
                  limit: Optional[int] = None,
                  after: Optional[Tuple[str, str]] = None):
        """Store.list_page parity over the paginated list endpoint."""
        from tf_operator_tpu.runtime.apiserver import (
            decode_continue,
            encode_continue,
        )

        query: Dict[str, str] = {}
        if namespace is not None:
            query["namespace"] = namespace
        if selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(selector.items()))
        if limit is not None:
            query["limit"] = str(limit)
        if after is not None:
            query["continue"] = encode_continue(after)
        data = self._request("GET", f"/apis/v1/{kind}", query=query)
        cls = self._cls(kind)
        items = [cls.from_dict(item) for item in data.get("items", [])]
        cont = data.get("continue") or ""
        next_after = decode_continue(cont) if cont else None
        return items, next_after, data.get("resourceVersion", 0)

    def list_claimable(self, kind: str, namespace: str,
                       selector: Dict[str, str],
                       owner_uid: str) -> List[object]:
        """Store.list_claimable parity for duck-typed consumers: label
        match OR owned by ``owner_uid`` (client-side filter over the
        namespace listing)."""
        out = []
        for obj in self.list(kind, namespace=namespace):
            if not store_mod.matches_selector(obj.metadata.labels, selector):
                ref = obj.metadata.controller_ref()
                if ref is None or ref.uid != owner_uid:
                    continue
            out.append(obj)
        return out

    def update(self, kind: str, obj) -> object:
        meta = obj.metadata
        data = self._request(
            "PUT", f"/apis/v1/{kind}/{meta.namespace}/{meta.name}",
            body=obj.to_dict())
        return self._cls(kind).from_dict(data)

    def update_status(self, kind: str, obj) -> object:
        meta = obj.metadata
        data = self._request(
            "PUT", f"/apis/v1/{kind}/{meta.namespace}/{meta.name}/status",
            body=obj.to_dict())
        return self._cls(kind).from_dict(data)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", f"/apis/v1/{kind}/{namespace}/{name}")

    def try_delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self.delete(kind, namespace, name)
            return True
        except store_mod.NotFoundError:
            return False

    def count(self, kind: str) -> int:
        return len(self.list(kind))

    def keys(self, kind: str) -> List[Tuple[str, str, int]]:
        return [(o.metadata.namespace, o.metadata.name,
                 o.metadata.resource_version) for o in self.list(kind)]

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, handler: Callable[[str, object], None],
              replay: bool = True,
              since_rv: Optional[int] = None) -> RemoteWatcher:
        # On first connect the server replays current objects as ADDED
        # (or, with since_rv, only events newer than it); reconnects
        # resume from the last resourceVersion seen on the stream.
        self._cls(kind)
        w = RemoteWatcher(self.base_url, kind, handler,
                          token=self.token, ssl_context=self._ssl,
                          since_rv=since_rv)
        with self._lock:
            self._watchers.append(w)
        return w

    def stop_watchers(self) -> None:
        with self._lock:
            watchers, self._watchers = self._watchers, []
        for w in watchers:
            w.stop()

    # -- logs (API-server log proxy; not part of the in-process Store) ----

    def read_logs(self, namespace: str, pod_name: str,
                  tail_lines: Optional[int] = None) -> str:
        query: Dict[str, str] = {}
        if tail_lines is not None:
            query["tailLines"] = str(tail_lines)
        url = f"{self.base_url}/logs/{namespace}/{pod_name}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        try:
            with self._open(url, self.timeout) as resp:
                return resp.read().decode(errors="replace")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return ""
            raise

    def stream_logs(self, namespace: str, pod_name: str
                    ) -> Iterator[str]:
        """Follow a pod's log live (kubectl logs -f analog): yields chunks
        until the stream ends (pod finished and log drained). No socket
        timeout: a training pod can be quiet for minutes between lines;
        the server closes the stream when the pod terminates."""
        url = (f"{self.base_url}/logs/{namespace}/{pod_name}?follow=1")
        resp = self._open(url, None)
        try:
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                yield chunk.decode(errors="replace")
        finally:
            resp.close()
