"""Structured logging: JSON formatter + contextual job/replica loggers.

Reference parity: logrus JSON setup with a filename hook
(cmd/tf-operator.v1/main.go:32-37,58-61) and the contextual field
loggers in vendored common/pkg/util/logger.go:26-96 (fields: job, uid,
replica-type, replica-index, pod).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
from typing import Optional

from tf_operator_tpu.runtime import trace as trace_mod


class JSONFormatter(logging.Formatter):
    """One JSON object per line: time/level/msg/filename plus any
    contextual fields attached via LoggerAdapter extras. Lines emitted
    inside a traced sync additionally carry ``trace_id``/``span`` from
    the ambient trace context (runtime/trace.py), so logs and
    ``/debug/traces`` cross-reference (docs/observability.md)."""

    _SKIP = frozenset(
        logging.makeLogRecord({}).__dict__) | {"message", "asctime"}

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": _dt.datetime.fromtimestamp(
                record.created, _dt.timezone.utc).isoformat(),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "filename": f"{record.filename}:{record.lineno}",
            "logger": record.name,
        }
        trace_id, span = trace_mod.current_ids()
        if trace_id:
            out["trace_id"] = trace_id
            out["span"] = span
        for k, v in record.__dict__.items():
            if k not in self._SKIP and not k.startswith("_"):
                out[k] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(json_format: bool = False,
                  level: int = logging.INFO) -> None:
    handler = logging.StreamHandler()
    if json_format:
        handler.setFormatter(JSONFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s %(filename)s:%(lineno)d] "
            "%(message)s"))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)


def logger_for_job(base: logging.Logger, job,
                   rtype: Optional[str] = None,
                   index: Optional[int] = None) -> logging.LoggerAdapter:
    """Contextual logger (reference LoggerForJob/LoggerForReplica,
    util/logger.go:46-96)."""
    extra = {
        "job": f"{job.metadata.namespace}.{job.metadata.name}",
        "uid": job.metadata.uid,
    }
    if rtype is not None:
        extra["replica_type"] = rtype
    if index is not None:
        extra["replica_index"] = index
    return logging.LoggerAdapter(base, extra)
