"""HTTP API server: the Store served over the network.

The reference's control plane is only reachable through a remote API
server (cmd/tf-operator.v1/app/server.go:72-229 builds clientsets against
kubeconfig; the SDK talks HTTPS from anywhere,
sdk/python/kubeflow/tfjob/api/tf_job_client.py:55-100). This module gives
the TPU-native Store the same property: REST CRUD over the existing serde
wire format plus a streaming watch, so SDK clients, node agents, and
dashboards run in separate processes (or hosts) from the operator.

Wire contract (all JSON):

  GET    /healthz                         -> {"status": "ok"}
  GET    /version                         -> {"version": ...}
  GET    /apis/v1/{kind}                  -> {"items": [...],
         "resourceVersion": N, "continue": token-or-""}
         ?namespace=ns&labelSelector=k=v,k2=v2&limit=N&continue=token
         (limit pages the keyset walk; pass the returned continue token
         to fetch the next page — every object present for the whole
         walk appears exactly once)
  POST   /apis/v1/{kind}                  -> created object
  GET    /apis/v1/{kind}/{ns}/{name}      -> object
  PUT    /apis/v1/{kind}/{ns}/{name}      -> updated object
  PUT    /apis/v1/{kind}/{ns}/{name}/status -> updated object
  DELETE /apis/v1/{kind}/{ns}/{name}      -> {}
  GET    /apis/v1/watch/{kind}            -> JSON-lines stream of
         {"type": ADDED|MODIFIED|DELETED, "object": {...}}; existing
         objects replay as ADDED; blank keepalive lines every few
         seconds. ?resourceVersion=N resumes from the store's watch
         log — only events newer than N replay (no ADDED storm); an
         RV already evicted from the log degrades to the full replay.
  GET    /logs/{ns}/{pod}?follow=1&tailLines=N -> text/plain pod log,
         proxied from the owning node agent (kubelet log API analog).

Errors: {"reason": NotFound|Conflict|AlreadyExists|BadRequest,
"message": ...} with status 404/409/409/400.

Security (round 5 — the reference rides the K8s API server, so every
hop there is TLS + bearer token + RBAC; this server carries its own
equivalents, runtime/tlsutil.py):

- TLS: pass ``tls_cert``/``tls_key`` (self-signed bootstrap via
  tlsutil.ensure_self_signed); the url property flips to https.
- Bearer tokens: pass ``tokens`` ({token: role}); every request except
  /healthz and /version (liveness probes) must carry
  ``Authorization: Bearer <token>``. Role ``read-only`` may GET/watch/
  read logs; writes need ``admin``. Missing/unknown token -> 401,
  insufficient role -> 403.
- Fail-closed default: binding a non-loopback address with no tokens
  configured rejects everything but /healthz//version with 401 unless
  ``insecure=True`` is passed explicitly (loopback binds stay open for
  same-host tooling — the kubectl-proxy convention).
"""

from __future__ import annotations

import base64
import hmac
import json
import logging
import socket
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Type

from tf_operator_tpu.api.serde import ApiObject
from tf_operator_tpu.api.types import (
    CheckpointRecord,
    ClusterQueue,
    Endpoint,
    EventRecord,
    Node,
    Pod,
    SliceGroup,
    TenantQueue,
    TPUJob,
)
from tf_operator_tpu.runtime import leaderelection, store as store_mod
from tf_operator_tpu.runtime.store import Store
from tf_operator_tpu.version import version_string

log = logging.getLogger("tpu_operator.apiserver")

# Collection name -> wire class. The schema registration analog
# (reference pkg/apis/tensorflow/v1/register.go).
WIRE_KINDS: Dict[str, Type[ApiObject]] = {
    store_mod.TPUJOBS: TPUJob,
    store_mod.PODS: Pod,
    store_mod.ENDPOINTS: Endpoint,
    store_mod.SLICEGROUPS: SliceGroup,
    store_mod.TENANTQUEUES: TenantQueue,
    store_mod.CLUSTERQUEUES: ClusterQueue,
    store_mod.CHECKPOINTRECORDS: CheckpointRecord,
    store_mod.EVENTS: EventRecord,
    store_mod.NODES: Node,
    leaderelection.LEASES: leaderelection.Lease,
}

_WATCH_KEEPALIVE_SECONDS = 3.0


def encode_continue(after) -> str:
    """Opaque continue token for list pagination: base64 of the last
    returned (namespace, name) key — the resume point of the store's
    keyset walk (K8s continue-token analog)."""
    return base64.urlsafe_b64encode(
        json.dumps(list(after)).encode()).decode()


def decode_continue(token: str):
    try:
        pair = json.loads(base64.urlsafe_b64decode(token.encode()))
        if (not isinstance(pair, list) or len(pair) != 2
                or not all(isinstance(x, str) for x in pair)):
            raise ValueError(pair)
        return tuple(pair)
    except Exception:
        raise _ApiError(400, "BadRequest",
                        f"malformed continue token {token!r}")


def parse_label_selector(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad labelSelector segment {part!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


class _ApiError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(message)
        self.code = code
        self.reason = reason
        self.message = message


def _store_call(fn, *args):
    """Run a store operation, mapping store errors to wire errors."""
    try:
        return fn(*args)
    except store_mod.AlreadyExistsError as e:
        raise _ApiError(409, "AlreadyExists", str(e))
    except store_mod.ConflictError as e:
        raise _ApiError(409, "Conflict", str(e))
    except store_mod.NotFoundError as e:
        raise _ApiError(404, "NotFound", str(e))


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpu-operator-api"

    # Set by APIServer via type():
    store: Store
    tokens: Optional[Dict[str, str]] = None   # token -> role
    anonymous_ok: bool = True                 # loopback bind or insecure

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("%s %s", self.address_string(), fmt % args)

    # -- authn/authz -------------------------------------------------------

    _OPEN_PATHS = (("healthz",), ("version",))

    def _authorize(self, parts, write: bool) -> None:
        """401 unauthenticated / 403 insufficient role. /healthz and
        /version stay open (liveness probes)."""
        if tuple(parts) in self._OPEN_PATHS:
            return
        if self.tokens is None:
            if self.anonymous_ok:
                return
            raise _ApiError(
                401, "Unauthorized",
                "this API server is bound to a non-loopback address "
                "with no authentication configured; configure bearer "
                "tokens (--api-tokens-file) or opt out explicitly "
                "(--api-insecure)")
        auth = self.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else ""
        # Constant-time comparison against EVERY stored token (the
        # hmac.compare_digest discipline ps.py/agent.py already follow):
        # a plain dict lookup leaks token-prefix timing, and an early
        # break would leak which token matched.
        role = None
        for stored, stored_role in self.tokens.items():
            if hmac.compare_digest(stored.encode(), token.encode()):
                role = stored_role
        if role is None:
            raise _ApiError(401, "Unauthorized",
                            "missing or invalid bearer token")
        if write and role != "admin":
            raise _ApiError(403, "Forbidden",
                            f"role {role!r} may not write")

    # -- plumbing ----------------------------------------------------------

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_obj(self, err: _ApiError) -> None:
        # An error decided BEFORE the body was read (401/403/404 on a
        # POST/PUT) must still consume it: HTTP/1.1 keep-alive parses
        # the next request from wherever this one's bytes ended, and an
        # unread body would desync the connection into spurious 400s.
        self._drain_body()
        self._send_json(err.code,
                        {"reason": err.reason, "message": err.message})

    def _drain_body(self) -> None:
        if getattr(self, "_body_consumed", False):
            return
        self._body_consumed = True
        length = int(self.headers.get("Content-Length", "0") or "0")
        while length > 0:
            chunk = self.rfile.read(min(length, 65536))
            if not chunk:
                break
            length -= len(chunk)

    def _read_body(self) -> dict:
        self._body_consumed = True
        length = int(self.headers.get("Content-Length", "0") or "0")
        raw = self.rfile.read(length) if length else b"{}"
        try:
            data = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            raise _ApiError(400, "BadRequest", f"invalid JSON body: {e}")
        if not isinstance(data, dict):
            raise _ApiError(400, "BadRequest", "body must be a JSON object")
        return data

    def _route(self):
        """(verb-agnostic) parse path -> (kind, cls, ns, name, subresource,
        query) or raise."""
        # One handler instance serves many keep-alive requests: reset
        # the per-request body-consumption flag (_drain_body contract).
        self._body_consumed = False
        parsed = urllib.parse.urlsplit(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = urllib.parse.parse_qs(parsed.query)
        return parts, query

    def _kind(self, kind: str) -> Type[ApiObject]:
        cls = WIRE_KINDS.get(kind)
        if cls is None:
            raise _ApiError(404, "NotFound", f"unknown kind {kind!r}")
        return cls

    def _decode(self, cls: Type[ApiObject], data: dict) -> ApiObject:
        try:
            return cls.from_dict(data)
        except (TypeError, ValueError) as e:
            raise _ApiError(400, "BadRequest",
                            f"cannot decode {cls.__name__}: {e}")

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        try:
            parts, query = self._route()
            self._authorize(parts, write=False)
            if parts == ["healthz"]:
                return self._send_json(200, {"status": "ok"})
            if parts == ["version"]:
                return self._send_json(200, {"version": version_string()})
            if len(parts) >= 2 and parts[:1] == ["logs"]:
                return self._serve_logs(parts[1:], query)
            if parts[:2] != ["apis", "v1"] or len(parts) < 3:
                raise _ApiError(404, "NotFound", f"no route {self.path}")
            rest = parts[2:]
            if rest[0] == "watch" and len(rest) == 2:
                return self._serve_watch(rest[1], query)
            if len(rest) == 1:        # list
                cls = self._kind(rest[0])
                ns = (query.get("namespace") or [None])[0]
                selector = None
                raw_sel = (query.get("labelSelector") or [None])[0]
                if raw_sel:
                    try:
                        selector = parse_label_selector(raw_sel)
                    except ValueError as e:
                        raise _ApiError(400, "BadRequest", str(e))
                limit = None
                raw_limit = (query.get("limit") or [None])[0]
                if raw_limit:
                    try:
                        limit = int(raw_limit)
                    except ValueError:
                        raise _ApiError(400, "BadRequest",
                                        f"invalid limit {raw_limit!r}")
                    if limit < 1:
                        raise _ApiError(400, "BadRequest",
                                        "limit must be >= 1")
                after = None
                raw_cont = (query.get("continue") or [None])[0]
                if raw_cont:
                    after = decode_continue(raw_cont)
                # Frozen snapshots straight out of the watch cache: the
                # page is serialized without a single deepcopy.
                items, next_after, rv = _store_call(
                    self.store.list_page, rest[0], ns, selector, limit,
                    after)
                return self._send_json(200, {
                    "items": [o.to_dict() for o in items],
                    "resourceVersion": rv,
                    "continue": (encode_continue(next_after)
                                 if next_after else ""),
                })
            if len(rest) == 3:        # get
                self._kind(rest[0])
                obj = _store_call(self.store.get, rest[0], rest[1], rest[2])
                return self._send_json(200, obj.to_dict())
            raise _ApiError(404, "NotFound", f"no route {self.path}")
        except _ApiError as e:
            self._send_error_obj(e)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self):
        try:
            parts, _ = self._route()
            self._authorize(parts, write=True)
            if parts[:2] != ["apis", "v1"] or len(parts) != 3:
                raise _ApiError(404, "NotFound", f"no route {self.path}")
            kind = parts[2]
            cls = self._kind(kind)
            obj = self._decode(cls, self._read_body())
            if not obj.metadata.name:
                raise _ApiError(400, "BadRequest", "metadata.name required")
            created = _store_call(self.store.create, kind, obj)
            self._send_json(201, created.to_dict())
        except _ApiError as e:
            self._send_error_obj(e)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_PUT(self):
        try:
            parts, _ = self._route()
            self._authorize(parts, write=True)
            if parts[:2] != ["apis", "v1"] or len(parts) not in (5, 6):
                raise _ApiError(404, "NotFound", f"no route {self.path}")
            kind, ns, name = parts[2], parts[3], parts[4]
            status_sub = len(parts) == 6
            if status_sub and parts[5] != "status":
                raise _ApiError(404, "NotFound", f"no route {self.path}")
            cls = self._kind(kind)
            obj = self._decode(cls, self._read_body())
            obj.metadata.namespace, obj.metadata.name = ns, name
            op = (self.store.update_status if status_sub
                  else self.store.update)
            updated = _store_call(op, kind, obj)
            self._send_json(200, updated.to_dict())
        except _ApiError as e:
            self._send_error_obj(e)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_DELETE(self):
        try:
            parts, _ = self._route()
            self._authorize(parts, write=True)
            if parts[:2] != ["apis", "v1"] or len(parts) != 5:
                raise _ApiError(404, "NotFound", f"no route {self.path}")
            kind, ns, name = parts[2], parts[3], parts[4]
            self._kind(kind)
            _store_call(self.store.delete, kind, ns, name)
            self._send_json(200, {})
        except _ApiError as e:
            self._send_error_obj(e)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- watch -------------------------------------------------------------

    def _serve_watch(self, kind: str, query) -> None:
        self._kind(kind)
        ns = (query.get("namespace") or [None])[0]
        since_rv = None
        raw_rv = (query.get("resourceVersion") or [None])[0]
        if raw_rv:
            try:
                since_rv = int(raw_rv)
            except ValueError:
                raise _ApiError(400, "BadRequest",
                                f"invalid resourceVersion {raw_rv!r}")
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonlines")
        self.send_header("Cache-Control", "no-cache")
        # Watch is a long-lived stream: no Content-Length, connection
        # closes when either side stops.
        self.send_header("Connection", "close")
        self.end_headers()

        import queue as _q
        events: "_q.Queue" = _q.Queue()
        # since_rv resumes from the store's watch log (replaying only
        # missed events) instead of a full ADDED storm; an evicted RV
        # silently degrades to the full replay.
        watcher = self.store.watch(kind,
                                   lambda et, obj: events.put((et, obj)),
                                   since_rv=since_rv)
        try:
            while True:
                try:
                    et, obj = events.get(timeout=_WATCH_KEEPALIVE_SECONDS)
                except _q.Empty:
                    self.wfile.write(b"\n")   # keepalive / liveness probe
                    self.wfile.flush()
                    continue
                if ns is not None and obj.metadata.namespace != ns:
                    continue
                line = json.dumps({"type": et, "object": obj.to_dict()})
                self.wfile.write(line.encode() + b"\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            watcher.stop()

    # -- log proxy ---------------------------------------------------------

    def _serve_logs(self, parts, query) -> None:
        if len(parts) != 2:
            raise _ApiError(404, "NotFound", f"no route {self.path}")
        ns, pod_name = parts
        pod = self.store.try_get(store_mod.PODS, ns, pod_name)
        if pod is None:
            raise _ApiError(404, "NotFound", f"pod {ns}/{pod_name} not found")
        node = None
        if pod.spec.node_name:
            node = self.store.try_get(store_mod.NODES, "default",
                                      pod.spec.node_name)
        if node is None or not node.status.log_url:
            # Same-host fallback: the local backend wrote log_path on
            # the pod status and shares a filesystem with the server.
            return self._serve_logs_local(pod, query)
        follow = (query.get("follow") or ["0"])[0] not in ("", "0", "false")
        qs = urllib.parse.urlencode(
            {k: v[0] for k, v in query.items()}, safe="=")
        url = f"{node.status.log_url}/logs/{ns}/{pod_name}"
        if qs:
            url = f"{url}?{qs}"
        try:
            # A follow stream can be idle for minutes between chunks —
            # no socket timeout (the agent closes it when the pod ends).
            upstream = urllib.request.urlopen(
                url, timeout=None if follow else 30)
        except OSError as e:
            raise _ApiError(502, "BadGateway",
                            f"node agent {pod.spec.node_name}: {e}")
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            while True:
                # read1: forward each upstream chunk as it arrives —
                # read(n) would buffer 64KB before sending anything,
                # stalling live follows.
                chunk = upstream.read1(65536)
                if not chunk:
                    break
                self.wfile.write(chunk)
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            upstream.close()

    def _serve_logs_local(self, pod: Pod, query) -> None:
        follow = (query.get("follow") or ["0"])[0] not in ("", "0", "false")
        if follow:
            return self._follow_logs_local(pod)
        path = pod.status.log_path
        text = b""
        if path:
            try:
                with open(path, "rb") as f:
                    text = f.read()
            except OSError:
                text = b""
        tail = (query.get("tailLines") or [None])[0]
        if tail is not None:
            try:
                n = int(tail)
            except ValueError:
                raise _ApiError(400, "BadRequest", "tailLines must be int")
            lines = text.splitlines()[-n:] if n > 0 else []
            text = b"\n".join(lines)
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(text)))
        self.end_headers()
        self.wfile.write(text)

    def _follow_logs_local(self, pod: Pod) -> None:
        """Live tail for pods run by the in-process backend (no node
        agent to proxy to): stream appended bytes until the pod reaches
        a terminal phase and the file is drained."""
        import time as _time

        from tf_operator_tpu.api.types import PodPhase

        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Connection", "close")
        self.end_headers()
        ns, name = pod.metadata.namespace, pod.metadata.name
        pos = 0
        try:
            while True:
                current = self.store.try_get(store_mod.PODS, ns, name)
                path = current.status.log_path if current else ""
                chunk = b""
                if path:
                    try:
                        with open(path, "rb") as f:
                            f.seek(pos)
                            chunk = f.read(65536)
                    except OSError:
                        pass
                if chunk:
                    pos += len(chunk)
                    self.wfile.write(chunk)
                    self.wfile.flush()
                    continue
                if current is None or current.status.phase in (
                        PodPhase.SUCCEEDED, PodPhase.FAILED):
                    return
                _time.sleep(0.05)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


def _is_loopback_host(host: str) -> bool:
    """Only a host that can ONLY be reached from this machine counts.
    '' and '::' are bind-ALL-interfaces conventions (ThreadingHTTPServer
    binds INADDR_ANY for ''; ps.py uses '' the same way), so they must
    fail closed — treating them as loopback would serve an
    unauthenticated API on every interface."""
    if host == "localhost":
        return True
    if host in ("", "::"):
        return False
    try:
        import ipaddress

        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False


class APIServer:
    """Serve a Store over HTTP(S) on a background thread (see module
    docstring for the auth/TLS contract)."""

    def __init__(self, store: Store, host: str = "127.0.0.1",
                 port: int = 0,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tokens: Optional[Dict[str, str]] = None,
                 insecure: bool = False):
        if bool(tls_cert) != bool(tls_key):
            raise ValueError("tls_cert and tls_key must be set together")
        handler = type("BoundHandler", (_Handler,), {
            "store": store,
            "tokens": dict(tokens) if tokens else None,
            "anonymous_ok": insecure or _is_loopback_host(host),
        })
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._tls = bool(tls_cert)
        if tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        if (tokens is None and not insecure
                and not _is_loopback_host(host)):
            log.warning(
                "API server binding %s with no authentication: all "
                "requests except /healthz//version will be rejected "
                "with 401 (configure tokens or pass insecure=True)",
                host)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="apiserver", daemon=True)
        self._thread.start()
        log.info("API server listening on %s", self.url)
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def wait_for_server(url: str, timeout: float = 10.0,
                    ca_file: Optional[str] = None) -> None:
    """Block until /healthz answers (process-startup rendezvous).
    /healthz is unauthenticated by design; ``ca_file`` verifies a
    self-signed TLS server."""
    import ssl
    import time

    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            ctx = None
            if url.startswith("https"):
                # Built inside the loop: with a self-signed bootstrap
                # the server process writes ca_file at startup, so it
                # may not exist on the first probes.
                ctx = ssl.create_default_context(cafile=ca_file)
            with urllib.request.urlopen(f"{url}/healthz", timeout=2,
                                        context=ctx) as r:
                if r.status == 200:
                    return
        except (OSError, socket.timeout) as e:
            last = e
        time.sleep(0.05)
    raise TimeoutError(f"API server at {url} not ready: {last}")
