"""Node agent: the kubelet analog for the served control plane.

The reference's data plane is kubelet: the operator writes Pods to the
API server, kubelet (on each node) runs the containers and reports
status back (SURVEY §3.2-3.3). This agent closes the same loop against
the served Store:

- registers a ``Node`` (address, chip capacity, log URL) and heartbeats;
- watches Pods, **claims** unbound ones by CAS-ing ``spec.node_name``
  (pull scheduling — optimistic-concurrency conflicts mean another agent
  won the pod, the all-or-nothing analog of kube-scheduler binding);
- at claim time publishes the pod's placement on its status: the node
  address and a freshly allocated host "coordinator" port;
- runs claimed pods with ``LocalProcessBackend``, resolving bootstrap
  env through the control plane instead of DNS: cluster names like
  ``{job}-worker-0.{ns}.svc`` resolve to the owning node's
  ``(status.host, status.ports)`` — real multi-host addresses, no
  loopback rewriting (kube-dns + headless-service analog);
- serves pod logs over HTTP (``/logs/{ns}/{pod}``, with ``?follow=1``
  live tail) so the API server can proxy them to SDK clients (the
  kubelet log API);
- relays checkpoint coordination (controller/ckpt.py) in both
  directions through the embedded ``LocalProcessBackend``: a preemption
  notice stamped on a pod (save-before-evict barrier) is forwarded to
  the worker process as a file (env ``TPUJOB_PREEMPT_FILE``), and the
  worker's checkpoint state file (``TPUJOB_CKPT_FILE`` — periodic
  saves, barrier acks, restore confirmations) is mirrored into the
  pod's ``CheckpointRecord`` on the control plane, exactly like pod
  phase reports.

Run as: ``python -m tf_operator_tpu.runtime.agent --server http://...``.
"""

from __future__ import annotations

import argparse
import datetime as _dt
import json
import logging
import os
import signal
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from tf_operator_tpu.api.types import Node, NodeSpec, NodeStatus, Pod
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import retry as retry_mod
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.local import LocalProcessBackend, _free_port
from tf_operator_tpu.runtime.remote import RemoteStore
from tf_operator_tpu.runtime.store import ADDED, MODIFIED

log = logging.getLogger("tpu_operator.agent")

HEARTBEAT_SECONDS = 5.0
RESOLVE_TIMEOUT_SECONDS = 120.0
COORDINATOR_PORT_NAME = "coordinator"

_ADDRESS_ENV_KEYS = ("JAX_COORDINATOR_ADDRESS",
                     "MEGASCALE_COORDINATOR_ADDRESS")


def _dns_pod_name(hostname: str) -> Tuple[str, str]:
    """``{pod}.{ns}.svc[.domain]`` -> (namespace, pod name)."""
    labels = hostname.split(".")
    if len(labels) >= 2:
        return labels[1], labels[0]
    return "default", labels[0]


class ControlPlaneEnvResolver:
    """Resolve bootstrap env through pod placement records.

    Peers' cluster DNS names map to the (host, port) the owning node
    published on the pod status. Blocks (bounded) until the referenced
    pods are claimed — the analog of DNS names only resolving once pods
    are scheduled, with connection retries replaced by an explicit wait.
    """

    def __init__(self, store, timeout: float = RESOLVE_TIMEOUT_SECONDS):
        self.store = store
        self.timeout = timeout

    def _placement(self, namespace: str, pod_name: str,
                   deadline: float) -> Tuple[str, Dict[str, int]]:
        while time.monotonic() < deadline:
            pod = self.store.try_get(store_mod.PODS, namespace, pod_name)
            if pod is not None and pod.status.host:
                return pod.status.host, dict(pod.status.ports)
            time.sleep(0.05)
        raise TimeoutError(
            f"pod {namespace}/{pod_name} was not placed within "
            f"{self.timeout}s; cannot resolve its address")

    def resolve(self, pod: Pod, env: Dict[str, str]) -> Dict[str, str]:
        deadline = time.monotonic() + self.timeout
        out = dict(env)
        host_cache: Dict[str, Tuple[str, Dict[str, int]]] = {}

        def placement(hostname: str) -> Tuple[str, Dict[str, int]]:
            if hostname not in host_cache:
                ns, name = _dns_pod_name(hostname)
                host_cache[hostname] = self._placement(ns, name, deadline)
            return host_cache[hostname]

        for key in _ADDRESS_ENV_KEYS:
            value = env.get(key)
            if not value:
                continue
            hostname, _, _default_port = value.partition(":")
            host, ports = placement(hostname)
            port = ports.get(COORDINATOR_PORT_NAME)
            if port is None:
                raise RuntimeError(
                    f"pod for {hostname} published no coordinator port")
            out[key] = f"{host}:{port}"
        if env.get("TPU_WORKER_HOSTNAMES"):
            out["TPU_WORKER_HOSTNAMES"] = ",".join(
                placement(h)[0]
                for h in env["TPU_WORKER_HOSTNAMES"].split(","))
        if env.get("TPUJOB_CLUSTER_SPEC"):
            out["TPUJOB_CLUSTER_SPEC"] = self._resolve_cluster_spec(
                env["TPUJOB_CLUSTER_SPEC"], placement)
        return out

    @staticmethod
    def _resolve_cluster_spec(raw: str, placement) -> str:
        """Rewrite the ps entries to published placements — the
        addresses tasks dial through the cluster spec (train/ps.py ps
        servers bind, workers' PSClient connects). Each claimed pod
        publishes one free port under the coordinator name
        (agent claim path); ps pods repurpose it as their serving port,
        so the same record resolves both sides. Other roles' entries
        stay DNS-named (identity, not dialed through the spec)."""
        import json as _json

        try:
            spec = _json.loads(raw)
        except ValueError:
            return raw
        cluster = spec.get("cluster") or {}
        if not cluster.get("ps"):
            return raw
        resolved = []
        for entry in cluster["ps"]:
            hostname = entry.rsplit(":", 1)[0]
            host, ports = placement(hostname)
            port = ports.get(COORDINATOR_PORT_NAME)
            if port is None:
                raise RuntimeError(
                    f"ps pod for {hostname} published no port")
            resolved.append(f"{host}:{port}")
        cluster["ps"] = resolved
        spec["cluster"] = cluster
        return _json.dumps(spec, sort_keys=True)


class _LogHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    agent: "NodeAgent"

    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = urllib.parse.parse_qs(parsed.query)
        # Capability-URL auth: the random path prefix is only published
        # in node.status.log_url behind the AUTHENTICATED control
        # plane, so direct unauthenticated reads from the network get
        # 404 (and learn nothing). hmac.compare_digest: no timing
        # oracle on the secret.
        import hmac

        if (len(parts) != 4 or parts[1] != "logs"
                or not hmac.compare_digest(parts[0],
                                           self.agent.log_secret)):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        ns, name = parts[2], parts[3]
        follow = (query.get("follow") or ["0"])[0] not in ("", "0", "false")
        tail = (query.get("tailLines") or [None])[0]
        try:
            self._serve(ns, name, follow, tail)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _serve(self, ns: str, name: str, follow: bool,
               tail: Optional[str]) -> None:
        path = self.agent.log_path_for(ns, name)
        if path is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if not follow:
            try:
                with open(path, "rb") as f:
                    text = f.read()
            except OSError:
                text = b""
            if tail is not None:
                lines = text.splitlines()[-max(0, int(tail)):]
                text = b"\n".join(lines)
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
            return
        # follow: stream appended bytes until the pod reaches a terminal
        # phase AND the file is drained (kubectl logs -f semantics).
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Connection", "close")
        self.end_headers()
        pos = 0
        while True:
            chunk = b""
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read(65536)
            except OSError:
                pass
            if chunk:
                pos += len(chunk)
                self.wfile.write(chunk)
                self.wfile.flush()
                continue
            if self.agent.pod_finished(ns, name):
                return
            time.sleep(0.05)


class NodeAgent:
    def __init__(self, server_url: str, name: Optional[str] = None,
                 address: str = "127.0.0.1", chips: int = 0,
                 workdir: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 log_port: int = 0,
                 resolve_timeout: float = RESOLVE_TIMEOUT_SECONDS,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        self.store = RemoteStore(server_url, token=token, ca_file=ca_file,
                                 insecure_skip_verify=insecure_skip_verify)
        self.name = name or f"node-{socket.gethostname()}-{os.getpid()}"
        self.address = address
        self.chips = chips
        self.backend = LocalProcessBackend(
            self.store, workdir=workdir, extra_env=extra_env,
            resolver=ControlPlaneEnvResolver(self.store,
                                             timeout=resolve_timeout),
            pod_filter=lambda pod: pod.spec.node_name == self.name)
        # Random capability prefix for the log server: only readers of
        # node.status.log_url (behind the authed control plane) can
        # construct valid log URLs — a bare network peer hitting the
        # port gets 404s. Rotates every agent restart.
        import secrets

        self.log_secret = secrets.token_urlsafe(16)
        handler = type("BoundLogHandler", (_LogHandler,), {"agent": self})
        self._log_httpd = ThreadingHTTPServer(("0.0.0.0", log_port), handler)
        self._log_httpd.daemon_threads = True
        self._threads: list = []
        self._claim_watcher = None
        self._stopped = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    @property
    def log_url(self) -> str:
        port = self._log_httpd.server_address[1]
        return f"http://{self.address}:{port}/{self.log_secret}"

    def start(self) -> "NodeAgent":
        self._register_node()
        t = threading.Thread(target=self._log_httpd.serve_forever,
                             name="agent-logs", daemon=True)
        t.start()
        self._threads.append(t)
        hb = threading.Thread(target=self._heartbeat_loop,
                              name="agent-heartbeat", daemon=True)
        hb.start()
        self._threads.append(hb)
        # Claim watcher first so pods get bound, then the backend (which
        # only reacts to pods already bound to this node).
        self._claim_watcher = self.store.watch(store_mod.PODS,
                                               self._on_pod_event)
        self.backend.start()
        log.info("node agent %s up (address=%s, logs=%s)",
                 self.name, self.address, self.log_url)
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._claim_watcher is not None:
            self._claim_watcher.stop()
        self.backend.stop()
        self.store.stop_watchers()
        self._log_httpd.shutdown()
        self._log_httpd.server_close()

    def _register_node(self) -> None:
        cpu, mem = _host_allocatable()
        node = Node(spec=NodeSpec(address=self.address, chips=self.chips),
                    status=NodeStatus(last_heartbeat=_now(),
                                      log_url=self.log_url,
                                      allocatable_cpu_millis=cpu,
                                      allocatable_memory_bytes=mem))
        node.metadata.name = self.name
        node.metadata.namespace = "default"

        def _register():
            existing = self.store.try_get(store_mod.NODES, "default",
                                          self.name)
            if existing is None:
                self.store.create(store_mod.NODES, node)
            else:
                node.metadata.resource_version = \
                    existing.metadata.resource_version
                self.store.update(store_mod.NODES, node)

        # Registration must survive a control-plane blip at agent boot:
        # without a Node record no pod ever lands here. Conflicts
        # (another register racing our read) retry through the re-read.
        retry_mod.with_retries(
            _register, policy=retry_mod.CLIENT_POLICY,
            component="agent.register",
            retryable=lambda e: (retry_mod.is_transient(e)
                                 or isinstance(e, (store_mod.ConflictError,
                                                   store_mod.AlreadyExistsError))))

    def _heartbeat_once(self) -> bool:
        def _beat():
            node = self.store.get(store_mod.NODES, "default", self.name)
            node.status.last_heartbeat = _now()
            node.status.log_url = self.log_url
            self.store.update_status(store_mod.NODES, node)

        try:
            retry_mod.with_retries(
                _beat, component="agent.heartbeat",
                retryable=lambda e: (retry_mod.is_transient(e)
                                     or isinstance(e,
                                                   store_mod.ConflictError)))
        except store_mod.NotFoundError:
            # The control plane restarted and lost our Node (or an
            # operator GC'd it): re-register instead of heartbeating
            # into the void forever.
            try:
                self._register_node()
            except Exception:
                log.warning("node re-registration failed", exc_info=True)
                return False
        except Exception:
            log.warning("heartbeat failed; node %s will look stale until "
                        "one lands", self.name, exc_info=True)
            return False
        metrics.node_agent_heartbeats.inc(node=self.name)
        return True

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(HEARTBEAT_SECONDS):
            self._heartbeat_once()

    # -- claiming ----------------------------------------------------------

    def _on_pod_event(self, event_type: str, pod: Pod) -> None:
        if self._stopped.is_set() or event_type not in (ADDED, MODIFIED):
            return
        if pod.spec.node_name:
            return  # already bound (possibly to us; backend handles it)
        threading.Thread(target=self._claim, args=(pod,),
                         daemon=True).start()

    def _claim(self, pod: Pod) -> None:
        """Bind an unscheduled pod to this node and publish its placement
        (address + allocated coordinator port) in one CAS update."""
        fresh = self.store.try_get(store_mod.PODS, pod.metadata.namespace,
                                   pod.metadata.name)
        if fresh is None or fresh.spec.node_name:
            return
        fresh.spec.node_name = self.name
        fresh.status.host = self.address
        fresh.status.ports = {COORDINATOR_PORT_NAME: _free_port()}
        try:
            # Transient API blips retry in place (a claim lost to a 500
            # is a pod nobody runs until the next watch event); Conflict
            # and NotFound stay semantic — another agent won, or the pod
            # vanished.
            retry_mod.with_retries(
                lambda: self.store.update(store_mod.PODS, fresh),
                component="agent.claim")
        except (store_mod.ConflictError, store_mod.NotFoundError):
            return
        except Exception:
            log.warning("claim of pod %s/%s failed after retries",
                        pod.metadata.namespace, pod.metadata.name,
                        exc_info=True)
            return
        log.info("claimed pod %s/%s", pod.metadata.namespace,
                 pod.metadata.name)

    # -- log server support ------------------------------------------------

    def log_path_for(self, namespace: str, name: str) -> Optional[str]:
        pod = self.store.try_get(store_mod.PODS, namespace, name)
        if pod is None:
            return None
        # Prefer the published status path (covers finished pods); fall
        # back to the deterministic path for pods that just started.
        if pod.status.log_path:
            return pod.status.log_path
        return self.backend.pod_log_path(pod)

    def pod_finished(self, namespace: str, name: str) -> bool:
        from tf_operator_tpu.api.types import PodPhase

        pod = self.store.try_get(store_mod.PODS, namespace, name)
        return pod is None or pod.status.phase in (PodPhase.SUCCEEDED,
                                                   PodPhase.FAILED)


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _host_allocatable() -> Tuple[Optional[int], Optional[int]]:
    """Best-effort host inventory (cpu millis, memory bytes) for the
    registered NodeStatus — the kubelet-allocatable analog the binder's
    fit filters consume. None (not 0) when the host doesn't expose it:
    unreported capacity must skip the fit check, not fail it."""
    cpu = os.cpu_count()
    cpu_millis = cpu * 1000 if cpu else None
    mem_bytes: Optional[int] = None
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        if pages > 0 and page_size > 0:
            mem_bytes = pages * page_size
    except (ValueError, OSError, AttributeError):
        pass
    return cpu_millis, mem_bytes


def main(argv=None) -> int:
    from tf_operator_tpu.runtime.logconfig import setup_logging

    parser = argparse.ArgumentParser(prog="tpu-node-agent")
    parser.add_argument("--server", required=True,
                        help="operator API server URL, e.g. http://op:8080")
    parser.add_argument("--name", default=None)
    parser.add_argument("--address", default="127.0.0.1",
                        help="address peers use to reach pods on this node")
    parser.add_argument("--chips", type=int, default=0)
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--log-port", type=int, default=0)
    parser.add_argument("--extra-env", default="",
                        help="JSON object of extra env for every pod")
    parser.add_argument("--token", default=None,
                        help="bearer token for the API server (admin "
                             "role: agents write pod/node state); "
                             "default $TPU_OPERATOR_TOKEN")
    parser.add_argument("--token-file", default=None,
                        help="read the bearer token from this file "
                             "(first line; wins over --token)")
    parser.add_argument("--ca-cert", default=None,
                        help="CA bundle to verify the API server's TLS "
                             "certificate (self-signed bootstrap)")
    parser.add_argument("--insecure-skip-tls-verify", action="store_true",
                        help="skip TLS verification (test/dev only)")
    parser.add_argument("--json-log-format", dest="json_log", default=True,
                        action=argparse.BooleanOptionalAction)
    args = parser.parse_args(argv)
    setup_logging(json_format=args.json_log)

    token = args.token or os.environ.get("TPU_OPERATOR_TOKEN") or None
    if args.token_file:
        from tf_operator_tpu.runtime.tlsutil import read_token

        token = read_token(args.token_file)
    extra_env = json.loads(args.extra_env) if args.extra_env else None
    agent = NodeAgent(args.server, name=args.name, address=args.address,
                      chips=args.chips, workdir=args.workdir,
                      extra_env=extra_env, log_port=args.log_port,
                      token=token, ca_file=args.ca_cert,
                      insecure_skip_verify=args.insecure_skip_tls_verify)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    agent.start()
    stop.wait()
    agent.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
