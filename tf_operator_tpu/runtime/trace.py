"""Control-plane flight recorder: reconcile tracing + decision journal.

The metric catalog (runtime/metrics.py) says HOW MUCH; nothing said
WHERE THE TIME WENT or WHY A JOB IS WAITING. This module adds the two
missing surfaces:

- **Spans** (``span(name, **attrs)``): contextvar-propagated trace
  context with deterministic ids, instrumenting the reconcile path —
  workqueue dequeue -> engine sync -> pod list/claim -> gang/quota
  pass -> checkpoint-barrier consults -> binder pass -> status writes —
  with every ``runtime/retry.py`` call a child span carrying its
  attempt count, so conflict loops and retry storms show up in the
  timeline instead of vanishing into ``api_retries_total``. Tracing is
  OFF by default; disabled, ``span()`` returns one shared no-op object
  (no allocation, no lock — near-zero cost on the hot path).

- **FlightRecorder**: completed root traces are retained under a
  keep-the-interesting-ones policy — always the slowest
  ``keep_slowest``, every errored trace, plus every ``sample_every``-th
  of the rest (the drop count is exported as
  ``trace_spans_dropped_total``). Cumulative per-span-name wall time
  (``phase_totals``) feeds bench_controlplane.py's phase attribution.
  Served as JSON at ``/debug/traces`` on the MonitoringServer and
  optionally streamed to a ``--trace-file`` JSONL.

- **DecisionJournal**: every admission defer/deny, barrier
  open/resolve, displacement, preemption, and resize decision appends
  a structured per-job record (kind, reason, message, trace id);
  consecutive identical decisions coalesce into one record with a
  count, so a level-triggered pass re-deriving the same block 50
  times is one journal line, not 50. Always on (it is the "why is my
  job Pending" answer and must not require tracing); queryable at
  ``/debug/jobs/<ns>/<name>`` and via ``TPUJobClient.explain``.

Log correlation: ``current_ids()`` is read by
``logconfig.JSONFormatter`` so every log line emitted inside a traced
sync carries ``trace_id``/``span`` and cross-references the recorded
trace (docs/observability.md).
"""

from __future__ import annotations

import contextvars
import heapq
import itertools
import json
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.runtime import metrics

# The active span of this thread/task (None = untraced).
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_operator_trace", default=None)

# Deterministic ids: a process-wide monotonic counter, not uuids — two
# runs of the same test produce the same id sequence, and ids sort in
# creation order.
_trace_seq = itertools.count(1)


class _NoopSpan:
    """The disabled-tracing span: one shared instance, every operation
    a no-op. ``span() is span()`` holding true IS the zero-overhead
    contract (pinned by tests/test_observability.py)."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()


class _TraceBuf:
    """One in-flight trace: its id, completed-span list, and span-id
    counter. Owned by the root span; handed to the recorder when the
    root exits."""

    __slots__ = ("trace_id", "spans", "_span_seq", "t0", "t0_unix")

    def __init__(self) -> None:
        self.trace_id = f"t{next(_trace_seq):08x}"
        self.spans: List[dict] = []
        self._span_seq = itertools.count(1)
        self.t0 = time.perf_counter()
        self.t0_unix = time.time()

    def next_span_id(self) -> str:
        return f"s{next(self._span_seq)}"


class _Span:
    """An active span (tracing enabled). Completed spans are appended
    to their trace's span list as plain dicts on exit — completion
    order, with relative start offsets for timeline reconstruction."""

    __slots__ = ("name", "attrs", "buf", "span_id", "parent_id",
                 "_t0", "_token", "_root", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        parent: Optional[_Span] = _CURRENT.get()
        if parent is None:
            self.buf = _TraceBuf()
            self.parent_id = ""
            self._root = True
        else:
            self.buf = parent.buf
            self.parent_id = parent.span_id
            self._root = False
        self.span_id = self.buf.next_span_id()
        self._t0 = time.perf_counter()
        self._token = _CURRENT.set(self)
        return self

    def set(self, **attrs) -> "_Span":
        self.attrs.update(attrs)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _CURRENT.reset(self._token)
        duration = time.perf_counter() - self._t0
        record = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": round((self._t0 - self.buf.t0) * 1e3, 3),
            "duration_ms": round(duration * 1e3, 3),
        }
        if self.attrs:
            record["attrs"] = self.attrs
        if exc is not None:
            record["error"] = f"{exc_type.__name__}: {exc}"
        self.buf.spans.append(record)
        recorder = self._tracer.recorder
        recorder.note_phase(self.name, duration)
        if self._root:
            recorder.on_trace_complete(self.buf, duration,
                                       errored=exc is not None)
        return False


class FlightRecorder:
    """Ring-buffer retention of completed traces + phase accounting.

    Retention: the ``keep_slowest`` slowest-ever roots (min-heap), the
    last ``keep_errored`` errored roots, and every ``sample_every``-th
    of the rest in a ``ring``-deep sample ring. Everything else is
    dropped and counted (``trace_spans_dropped_total``) — at 10k-job
    scale the interesting syncs are the slow and broken ones, and a
    uniform sample preserves the baseline for comparison."""

    def __init__(self, keep_slowest: int = 32, keep_errored: int = 64,
                 sample_every: int = 16, ring: int = 128):
        self.keep_slowest = keep_slowest
        self.keep_errored = keep_errored
        self.sample_every = max(1, sample_every)
        self._lock = threading.Lock()
        # (duration, seq, trace_dict) min-heap: root of the heap is the
        # fastest of the retained-slowest, evicted first.
        self._slowest: List[Tuple[float, int, dict]] = []
        self._errored: deque = deque(maxlen=keep_errored)
        self._sampled: deque = deque(maxlen=ring)
        self._seen = 0
        self._heap_seq = itertools.count()
        self._phase_totals: Dict[str, float] = {}
        self._trace_file = None
        self._file_lock = threading.Lock()

    # -- ingestion -------------------------------------------------------

    def note_phase(self, name: str, seconds: float) -> None:
        """Accumulate wall time under a phase/span name. Called for
        every completed span and for phases that are not spans of one
        sync (``queue_wait`` from the workqueue, ``api_retry`` backoff
        sleeps, ``barrier_wait`` open->resolve elapsed)."""
        with self._lock:
            self._phase_totals[name] = \
                self._phase_totals.get(name, 0.0) + seconds

    def on_trace_complete(self, buf: _TraceBuf, duration: float,
                          errored: bool) -> None:
        trace = {
            "trace_id": buf.trace_id,
            "root": buf.spans[-1]["name"] if buf.spans else "",
            "start_unix": round(buf.t0_unix, 6),
            "duration_ms": round(duration * 1e3, 3),
            "errored": errored,
            "spans": buf.spans,
        }
        dropped_spans = 0
        with self._lock:
            self._seen += 1
            if errored:
                self._errored.append(trace)
            elif (len(self._slowest) < self.keep_slowest
                    or duration > self._slowest[0][0]):
                entry = (duration, next(self._heap_seq), trace)
                if len(self._slowest) < self.keep_slowest:
                    heapq.heappush(self._slowest, entry)
                else:
                    evicted = heapq.heapreplace(self._slowest, entry)
                    dropped_spans = len(evicted[2]["spans"])
            elif self._seen % self.sample_every == 0:
                if len(self._sampled) == self._sampled.maxlen:
                    dropped_spans = len(self._sampled[0]["spans"])
                self._sampled.append(trace)
            else:
                dropped_spans = len(buf.spans)
        if dropped_spans:
            metrics.trace_spans_dropped.inc(dropped_spans)
        self._stream(trace)

    def _stream(self, trace: dict) -> None:
        with self._file_lock:
            f = self._trace_file
            if f is None:
                return
            try:
                f.write(json.dumps(trace, sort_keys=True) + "\n")
                f.flush()
            except OSError:
                pass  # a full/yanked disk must not take down syncs

    # -- configuration ---------------------------------------------------

    def open_trace_file(self, path: Optional[str]) -> None:
        with self._file_lock:
            if self._trace_file is not None:
                try:
                    self._trace_file.close()
                except OSError:
                    pass
                self._trace_file = None
            if path:
                self._trace_file = open(path, "a", encoding="utf-8")

    # -- reads -----------------------------------------------------------

    def phase_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._phase_totals)

    def snapshot(self, limit: int = 256) -> dict:
        """The /debug/traces payload: retained traces (slowest first,
        then errored, then the sample ring newest-first), capped."""
        with self._lock:
            slow = [t for _, _, t in
                    sorted(self._slowest, reverse=True)]
            errored = list(self._errored)
            sampled = list(self._sampled)[::-1]
            seen = self._seen
            totals = {k: round(v, 6)
                      for k, v in sorted(self._phase_totals.items())}
        traces = (slow + errored + sampled)[:limit]
        return {
            "traces": traces,
            "retained": {"slowest": len(slow), "errored": len(errored),
                         "sampled": len(sampled)},
            "traces_seen": seen,
            "phase_totals_s": totals,
        }

    def reset(self) -> None:
        with self._lock:
            self._slowest.clear()
            self._errored.clear()
            self._sampled.clear()
            self._seen = 0
            self._phase_totals.clear()


class Tracer:
    """The span factory. ``enabled`` is the only hot-path check: off,
    ``span()`` hands back the shared no-op."""

    def __init__(self, recorder: Optional[FlightRecorder] = None):
        self.enabled = False
        self.recorder = recorder or FlightRecorder()

    def span(self, name: str, **attrs):
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, attrs)


class DecisionJournal:
    """Per-job ring of structured control-plane decisions — the
    operator-side answer to "why is my job Pending" (no log
    archaeology). Always on: recording is a dict append under one lock,
    and level-triggered re-derivations coalesce (same kind+reason as
    the newest record bumps ``count`` and refreshes ``message``/
    ``last_time`` instead of appending).

    Bounded twice: ``per_job`` records per job (oldest dropped) and
    ``max_jobs`` jobs total (least-recently-touched job dropped) — the
    journal can never grow past ~max_jobs*per_job records no matter
    how long the operator runs. Job GC prunes entries with the job
    (tpu_controller._on_job_event)."""

    def __init__(self, per_job: int = 128, max_jobs: int = 4096):
        self.per_job = per_job
        self.max_jobs = max_jobs
        self._lock = threading.Lock()
        self._jobs: "OrderedDict[Tuple[str, str], deque]" = OrderedDict()
        self._seq = itertools.count(1)

    def record(self, namespace: str, name: str, kind: str, reason: str,
               message: str = "", **attrs) -> None:
        now = time.time()
        trace_id, span_name = current_ids()
        key = (namespace, name)
        with self._lock:
            dq = self._jobs.get(key)
            if dq is None:
                dq = deque(maxlen=self.per_job)
                self._jobs[key] = dq
                while len(self._jobs) > self.max_jobs:
                    self._jobs.popitem(last=False)
            else:
                self._jobs.move_to_end(key)
            if dq:
                last = dq[-1]
                if last["kind"] == kind and last["reason"] == reason:
                    last["count"] += 1
                    last["last_time"] = now
                    last["message"] = message
                    if trace_id:
                        last["trace_id"] = trace_id
                    return
            rec = {
                "seq": next(self._seq),
                "time": now,
                "last_time": now,
                "kind": kind,
                "reason": reason,
                "message": message,
                "trace_id": trace_id,
                "span": span_name,
                "count": 1,
            }
            if attrs:
                rec["attrs"] = attrs
            dq.append(rec)

    def decisions(self, namespace: str, name: str) -> Optional[List[dict]]:
        """The job's decision records oldest-first, or None when the
        journal has never seen the job (the endpoint's 404)."""
        with self._lock:
            dq = self._jobs.get((namespace, name))
            if dq is None:
                return None
            return [dict(r) for r in dq]

    def prune(self, namespace: str, name: str) -> None:
        with self._lock:
            self._jobs.pop((namespace, name), None)

    def reset(self) -> None:
        with self._lock:
            self._jobs.clear()


# -- process-wide instances (the metrics.REGISTRY convention) -------------

RECORDER = FlightRecorder()
TRACER = Tracer(RECORDER)
JOURNAL = DecisionJournal()


def span(name: str, **attrs):
    """Module-level convenience: ``with trace.span("gang.sync"): ...``"""
    if not TRACER.enabled:
        return NOOP_SPAN
    return _Span(TRACER, name, attrs)


def note_phase(name: str, seconds: float) -> None:
    """Attribute non-span wall time to a phase (no-op when disabled)."""
    if TRACER.enabled:
        RECORDER.note_phase(name, seconds)


def enabled() -> bool:
    return TRACER.enabled


def current_ids() -> Tuple[str, str]:
    """(trace id, span name) of the calling context, ("", "") when
    untraced. Read by the JSON log formatter and the decision journal."""
    cur = _CURRENT.get()
    if cur is None:
        return "", ""
    return cur.buf.trace_id, cur.name


def configure(enabled: bool, trace_file: Optional[str] = None) -> None:
    """Wire tracing on/off (cli.py --enable-tracing / --trace-file).
    Enabling resets nothing; disabling leaves retained traces readable
    at /debug/traces."""
    RECORDER.open_trace_file(trace_file if enabled else None)
    TRACER.enabled = enabled


def reset_for_tests() -> None:
    """Drop all recorded state and disable tracing (test isolation)."""
    configure(False)
    RECORDER.reset()
    JOURNAL.reset()
