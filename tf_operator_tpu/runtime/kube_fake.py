"""In-process fake Kubernetes API server for backend tests.

The reference tests its K8s write path with generated fake clientsets
(pkg/client/clientset/versioned/fake/) and its e2e against a live GKE
cluster. Here the seam sits one level lower — a real HTTP server speaking
the small API subset KubeClient uses — so the exact production client,
informer, and controls are exercised byte-for-byte (the kind-cluster
analog, hermetic and millisecond-fast):

  POST/GET/DELETE/PATCH  /api/v1/namespaces/{ns}/{pods|services|events}
  GET list (+labelSelector) on namespaced and cluster scope
  GET ?watch=1 JSON-lines stream (blank-line keepalives)
  /apis/tpu-operator.dev/v1/.../tpujobs (+ /status subresource patch)
  PATCH is application/merge-patch+json (RFC 7386)

RBAC is ENFORCED: the fake loads ``manifests/base/rbac.yaml`` (the
ClusterRole the operator actually deploys with) and answers any request
outside the granted verbs with 403 Forbidden, exactly like a real
apiserver running the operator under its ServiceAccount — so a
manifest/RBAC drift (a new write path without a new verb) fails the
hermetic e2e suite instead of surfacing on a real cluster. Pass
``rbac_path=None`` to run permissive, or point it at an alternate
manifest to test tightened roles.

The fake also plays kubelet: ``set_pod_phase`` fabricates the
containerStatuses a node would report, which is how tests drive the
lifecycle (the reference e2e does this through its Flask test-server's
/exit endpoint; test/test-server/test_app.py:17-60).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import queue as _q
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api import constants

log = logging.getLogger("tpu_operator.kube_fake")

_KEEPALIVE_SECONDS = 2.0

RESOURCES = ("pods", "services", "events", "leases",
             "poddisruptionbudgets", "nodes", constants.PLURAL)

# Cluster-scoped resources live under the "" namespace key.
_CLUSTER_SCOPED = ("nodes",)

# API group per served resource (RBAC rule lookup key).
_RESOURCE_GROUPS = {
    "pods": "", "services": "", "events": "", "nodes": "",
    "leases": "coordination.k8s.io",
    "poddisruptionbudgets": "policy",
    "customresourcedefinitions": "apiextensions.k8s.io",
    constants.PLURAL: constants.GROUP,
}

# The checked-in ClusterRole the fake enforces by default.
DEFAULT_RBAC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "manifests", "base", "rbac.yaml")


def load_rbac_rules(path: str) -> Dict[Tuple[str, str], set]:
    """Parse ClusterRole rules out of an RBAC manifest into
    {(apiGroup, resource): {verbs}} — subresources keep their
    ``resource/sub`` names, exactly as K8s RBAC scopes them."""
    import yaml

    rules: Dict[Tuple[str, str], set] = {}
    with open(path) as f:
        for doc in yaml.safe_load_all(f):
            if (doc or {}).get("kind") != "ClusterRole":
                continue
            for rule in doc.get("rules") or []:
                verbs = set(rule.get("verbs") or [])
                for g in rule.get("apiGroups") or []:
                    for r in rule.get("resources") or []:
                        rules.setdefault((g, r), set()).update(verbs)
    return rules


def _default_ns(resource: str, ns) -> str:
    if resource in _CLUSTER_SCOPED:
        return ""
    return ns or "default"


def merge_patch(target, patch):
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


def _match_selector(labels: Dict[str, str], raw: str) -> bool:
    if not raw:
        return True
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        if labels.get(k.strip()) != v.strip():
            return False
    return True


def _status_body(code: int, reason: str, message: str) -> dict:
    """core/v1 Status error shape real API servers return."""
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "message": message, "reason": reason, "code": code}


class _HttpError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(message)
        self.code = code
        self.reason = reason
        self.message = message


class FakeKubeState:
    """The etcd analog: objects + watch fan-out, shared by all handler
    threads."""

    def __init__(self):
        self.lock = threading.RLock()
        # resource -> {(ns, name) -> dict}
        self.objects: Dict[str, Dict[Tuple[str, str], dict]] = {
            r: {} for r in RESOURCES}
        # RBAC enforcement: {(apiGroup, resource): {verbs}} from the
        # deployed ClusterRole (load_rbac_rules). None = permissive.
        self.rbac_rules: Optional[Dict[Tuple[str, str], set]] = None
        self._rv = 0
        # (resource, queue) watch subscriptions
        self._watchers: List[Tuple[str, "_q.Queue"]] = []
        # (ns, pod) -> log text, the fake kubelet's log store.
        self.pod_logs: Dict[Tuple[str, str], str] = {}
        # --- chaos injection (reflector-hardening tests) ---------------
        # Watches started with resourceVersion < compact_rv get an
        # immediate ERROR 410 ("too old resource version") — the real
        # apiserver's etcd-compaction behavior.
        self.compact_rv = 0
        # Count of watch ERROR events to inject mid-stream: each watch
        # delivery decrements it and sends {"code": watch_error_code}
        # instead of the event (the event itself is NOT delivered — the
        # client must recover it by relist/resume).
        self.inject_watch_errors = 0
        self.watch_error_code = 410
        # Drop the next N watch events silently (network blip analog:
        # the client sees nothing and must reconcile via relist).
        self.drop_events = 0
        # Reorder pairs: hold back the next event and deliver it AFTER
        # the one following it, N times.
        self.reorder_events = 0
        self._held_event: Optional[Tuple[str, dict]] = None
        # Per-resource list-request counter (watch-resume assertions:
        # proves the reflector did NOT relist).
        self.list_counts: Dict[str, int] = {}
        # --- round-5 meanness -----------------------------------------
        # Answer the next N non-watch requests with 429 + Retry-After
        # (apiserver priority-and-fairness throttling analog).
        self.inject_429 = 0
        self.retry_after_seconds = 1
        self.throttled_requests = 0  # how many 429s were served
        # Answer the next N non-watch requests with 500 (apiserver
        # blip / upstream etcd error burst).
        self.inject_5xx = 0
        # Fixed added latency per request (models a loaded production
        # apiserver; tens of ms is realistic).
        self.latency_seconds = 0.0
        # --- seeded FaultProfile (runtime/chaos.py) -------------------
        # The deterministic successor to the one-shot knobs above:
        # per-verb/per-kind error RATES (write/read 5xx, 409 conflicts,
        # timeouts/connection drops, stale reads, watch-stream deaths)
        # drawn from one seeded RNG, so a whole chaos campaign is
        # reproducible from its seed. None = no probabilistic faults;
        # the counter knobs keep working either way (tests compose
        # both). set_fault_profile() installs it.
        self.fault_injector = None
        # (resource, (ns, name)) -> previous stored object, feeding
        # stale reads (a lagging watch-cache / follower-read analog).
        self.object_history: Dict[Tuple[str, Tuple[str, str]], dict] = {}

    def set_fault_profile(self, profile) -> "object":
        """Install a seeded ``chaos.FaultProfile`` (None clears).
        Returns the injector so tests can read its per-fault counts."""
        if profile is None:
            self.fault_injector = None
            return None
        from tf_operator_tpu.runtime.chaos import FaultInjector

        self.fault_injector = FaultInjector(profile)
        return self.fault_injector

    def _remember(self, resource: str, key: Tuple[str, str]) -> None:
        """Stash the current version before a mutation (stale-read
        pool). Caller holds the lock."""
        inj = self.fault_injector
        if inj is None or inj.profile.rate("stale_read") <= 0.0:
            return
        cur = self.objects[resource].get(key)
        if cur is not None:
            self.object_history[(resource, key)] = json.loads(
                json.dumps(cur))

    def next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    # -- RBAC --------------------------------------------------------------

    def authorize(self, resource: str, verb: str,
                  subresource: str = "") -> None:
        """403 unless the loaded ClusterRole grants ``verb`` on the
        resource (subresources are their own RBAC names, e.g.
        ``pods/binding``). No rules loaded = permissive (unit tests
        driving the state directly, or rbac_path=None)."""
        rules = self.rbac_rules
        if rules is None:
            return
        group = _RESOURCE_GROUPS.get(resource, "")
        name = f"{resource}/{subresource}" if subresource else resource
        for key in ((group, name), ("*", name), (group, "*"), ("*", "*")):
            verbs = rules.get(key)
            if verbs and ("*" in verbs or verb in verbs):
                return
        raise _HttpError(
            403, "Forbidden",
            f'operator cannot {verb} resource "{name}" in API group '
            f'"{group}": not granted by the deployed ClusterRole '
            "(manifests/base/rbac.yaml) — add the verb there if the "
            "operator legitimately needs it")

    # -- CRUD (all under lock) --------------------------------------------

    def create(self, resource: str, ns: str, obj: dict) -> dict:
        with self.lock:
            name = (obj.get("metadata") or {}).get("name", "")
            if not name:
                raise _HttpError(400, "Invalid", "metadata.name required")
            key = (ns, name)
            if key in self.objects[resource]:
                raise _HttpError(409, "AlreadyExists",
                                 f"{resource} {ns}/{name} already exists")
            obj = json.loads(json.dumps(obj))  # detach
            meta = obj.setdefault("metadata", {})
            meta["namespace"] = ns
            meta.setdefault("uid", str(uuid.uuid4()))
            meta["resourceVersion"] = self.next_rv()
            meta.setdefault("creationTimestamp",
                            _dt.datetime.now(_dt.timezone.utc)
                            .strftime("%Y-%m-%dT%H:%M:%SZ"))
            if resource == "pods":
                obj.setdefault("status", {"phase": "Pending"})
            self.objects[resource][key] = obj
            self._notify(resource, "ADDED", obj)
            return json.loads(json.dumps(obj))

    def get(self, resource: str, ns: str, name: str) -> dict:
        with self.lock:
            obj = self.objects[resource].get((ns, name))
            if obj is None:
                raise _HttpError(404, "NotFound",
                                 f"{resource} {ns}/{name} not found")
            inj = self.fault_injector
            if inj is not None and inj.decide("stale_read", "get",
                                              resource):
                stale = self.object_history.get((resource, (ns, name)))
                if stale is not None:
                    return json.loads(json.dumps(stale))
            return json.loads(json.dumps(obj))

    def delete(self, resource: str, ns: str, name: str) -> dict:
        with self.lock:
            obj = self.objects[resource].pop((ns, name), None)
            if obj is None:
                raise _HttpError(404, "NotFound",
                                 f"{resource} {ns}/{name} not found")
            self._notify(resource, "DELETED", obj)
            return _status_body(200, "Deleted", f"{name} deleted") | {
                "status": "Success"}

    def patch(self, resource: str, ns: str, name: str, patch: dict,
              subresource: str = "") -> dict:
        with self.lock:
            cur = self.objects[resource].get((ns, name))
            if cur is None:
                raise _HttpError(404, "NotFound",
                                 f"{resource} {ns}/{name} not found")
            # resourceVersion in a patch is an optimistic-concurrency
            # precondition (real apiserver semantics).
            want_rv = (patch.get("metadata") or {}).get("resourceVersion")
            cur_rv = (cur.get("metadata") or {}).get("resourceVersion", "")
            if want_rv and want_rv != cur_rv:
                raise _HttpError(409, "Conflict",
                                 f"resourceVersion {want_rv} != {cur_rv}")
            if subresource == "status":
                # Status subresource: only .status merges.
                patch = {"status": patch.get("status")}
            self._remember(resource, (ns, name))
            merged = merge_patch(cur, patch)
            meta = merged.setdefault("metadata", {})
            meta["name"], meta["namespace"] = name, ns
            meta["uid"] = (cur.get("metadata") or {}).get("uid", "")
            meta["resourceVersion"] = self.next_rv()
            self.objects[resource][(ns, name)] = merged
            self._notify(resource, "MODIFIED", merged)
            return json.loads(json.dumps(merged))

    def replace(self, resource: str, ns: str, name: str, obj: dict) -> dict:
        """PUT with optimistic concurrency: a stale resourceVersion loses
        the race (the CAS leader election depends on)."""
        with self.lock:
            cur = self.objects[resource].get((ns, name))
            if cur is None:
                raise _HttpError(404, "NotFound",
                                 f"{resource} {ns}/{name} not found")
            rv = (obj.get("metadata") or {}).get("resourceVersion", "")
            cur_rv = (cur.get("metadata") or {}).get("resourceVersion", "")
            if rv and rv != cur_rv:
                raise _HttpError(409, "Conflict",
                                 f"resourceVersion {rv} != {cur_rv}")
            self._remember(resource, (ns, name))
            obj = json.loads(json.dumps(obj))
            meta = obj.setdefault("metadata", {})
            meta["name"], meta["namespace"] = name, ns
            meta["uid"] = (cur.get("metadata") or {}).get("uid", "")
            meta["creationTimestamp"] = (cur.get("metadata") or {}).get(
                "creationTimestamp", "")
            meta["resourceVersion"] = self.next_rv()
            self.objects[resource][(ns, name)] = obj
            self._notify(resource, "MODIFIED", obj)
            return json.loads(json.dumps(obj))

    def list(self, resource: str, ns: Optional[str],
             selector: str, field_selector: str = "") -> dict:
        with self.lock:
            items = []
            for (ons, _), obj in self.objects[resource].items():
                if ns is not None and ons != ns:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if not _match_selector(labels, selector):
                    continue
                if field_selector and not self._match_fields(obj,
                                                             field_selector):
                    continue
                items.append(json.loads(json.dumps(obj)))
            return {"kind": "List", "apiVersion": "v1",
                    "metadata": {"resourceVersion": str(self._rv)},
                    "items": items}

    @staticmethod
    def _match_fields(obj: dict, raw: str) -> bool:
        """The fieldSelector subset real clients use on Events:
        dotted-path equality (e.g. involvedObject.name=job)."""
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            path, _, want = part.partition("=")
            node = obj
            for seg in path.split("."):
                node = node.get(seg, {}) if isinstance(node, dict) else {}
            if (node if isinstance(node, str) else "") != want:
                return False
        return True

    # -- watch -------------------------------------------------------------

    def subscribe(self, resource: str) -> "_q.Queue":
        q: "_q.Queue" = _q.Queue()
        with self.lock:
            self._watchers.append((resource, q))
        return q

    def unsubscribe(self, q: "_q.Queue") -> None:
        with self.lock:
            self._watchers = [(r, w) for r, w in self._watchers if w is not q]

    def _notify(self, resource: str, etype: str, obj: dict) -> None:
        payload = json.loads(json.dumps(obj))
        for r, q in self._watchers:
            if r == resource:
                q.put((etype, payload))

    # -- fake kubelet ------------------------------------------------------

    def set_pod_log(self, ns: str, name: str, text: str) -> None:
        """Fake kubelet log store (served by GET .../pods/{name}/log)."""
        with self.lock:
            self.pod_logs[(ns, name)] = text

    def append_pod_log(self, ns: str, name: str, text: str) -> None:
        with self.lock:
            self.pod_logs[(ns, name)] = self.pod_logs.get((ns, name),
                                                          "") + text

    def set_pod_phase(self, ns: str, name: str, phase: str,
                      exit_code: Optional[int] = None,
                      restart_count: int = 0) -> None:
        """Fabricate the node's status report for a pod."""
        with self.lock:
            pod = self.objects["pods"].get((ns, name))
            if pod is None:
                raise _HttpError(404, "NotFound", f"pod {ns}/{name} not found")
            containers = (pod.get("spec") or {}).get("containers") or []
            statuses = []
            for c in containers:
                if phase in ("Succeeded", "Failed"):
                    code = exit_code if exit_code is not None else (
                        0 if phase == "Succeeded" else 1)
                    state = {"terminated": {"exitCode": code}}
                elif phase == "Running":
                    state = {"running": {}}
                else:
                    state = {"waiting": {"reason": "ContainerCreating"}}
                statuses.append({"name": c.get("name", ""), "state": state,
                                 "restartCount": restart_count})
            self.patch("pods", ns, name,
                       {"status": {"phase": phase, "hostIP": "10.0.0.1",
                                   "containerStatuses": statuses}},
                       subresource="status")

    def add_node(self, name: str, chips: int = 8, ici_domain: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 unschedulable: bool = False, ready: bool = True,
                 taints: Optional[list] = None,
                 cpu: Optional[str] = None,
                 memory: Optional[str] = None) -> dict:
        """Register a core/v1 Node the way a kubelet + TPU device plugin
        would: allocatable google.com/tpu chips plus the ICI-domain
        label the gang binder keys slice affinity on. A heartbeating
        kubelet reports a Ready condition (``ready=False`` models a dead
        kubelet; a node with NO Ready condition at all — kubelet never
        heartbeated — is built by passing ``ready=None``). ``taints``
        is a list of core/v1 taint dicts ({key, value, effect});
        ``cpu``/``memory`` are allocatable quantity strings ("4",
        "500m", "16Gi") — binds violating any of these are rejected the
        way kubelet/kube-scheduler would reject them (422)."""
        node_labels = dict(labels or {})
        if ici_domain:
            node_labels[constants.LABEL_ICI_DOMAIN] = ici_domain
        allocatable: dict = {constants.RESOURCE_TPU: str(chips)}
        if cpu is not None:
            allocatable["cpu"] = str(cpu)
        if memory is not None:
            allocatable["memory"] = str(memory)
        status: dict = {"allocatable": allocatable,
                        "addresses": [{"type": "InternalIP",
                                       "address": "10.0.0.1"}]}
        if ready is not None:
            status["conditions"] = [{"type": "Ready",
                                     "status": "True" if ready else "False"}]
        spec: dict = {"unschedulable": unschedulable}
        if taints:
            spec["taints"] = [dict(t) for t in taints]
        obj = {"apiVersion": "v1", "kind": "Node",
               "metadata": {"name": name, "labels": node_labels},
               "spec": spec,
               "status": status}
        return self.create("nodes", "", obj)

    def cordon_node(self, name: str, unschedulable: bool = True) -> dict:
        return self.patch("nodes", "", name,
                          {"spec": {"unschedulable": unschedulable}})

    def set_node_condition(self, name: str, ctype: str,
                           status: str = "True",
                           reason: str = "") -> dict:
        """Upsert one node condition the way a kubelet / node-problem-
        detector status write would (merge patch replaces the whole
        conditions list, so read-modify-write under the lock)."""
        with self.lock:
            node = self.objects["nodes"].get(("", name))
            if node is None:
                raise _HttpError(404, "NotFound", f"node {name} not found")
            conditions = list((node.get("status") or {})
                              .get("conditions") or [])
            conditions = [c for c in conditions if c.get("type") != ctype]
            cond = {"type": ctype, "status": status}
            if reason:
                cond["reason"] = reason
            conditions.append(cond)
            return self.patch("nodes", "", name,
                              {"status": {"conditions": conditions}},
                              subresource="status")

    def inject_maintenance(self, name: str,
                           reason: str = "ScheduledMaintenance") -> dict:
        """TPU maintenance notice: the node is still Ready and serving,
        but the platform has announced an upcoming disruption (GKE
        surfaces these ahead of TPU maintenance events). The slice-health
        controller cordons and drains off it."""
        return self.set_node_condition(name, "MaintenancePending",
                                       "True", reason=reason)

    def inject_preemption(self, name: str,
                          reason: str = "SpotPreemption") -> dict:
        """Spot/preemptible termination notice (the ~30s ACPI warning
        surfaced as a condition): the node is about to vanish."""
        return self.set_node_condition(name, "TerminationScheduled",
                                       "True", reason=reason)

    def bind_pod(self, ns: str, name: str, node: str) -> dict:
        """Bindings-API core: assign the pod to a node exactly once (a
        real apiserver 409s a second bind — two schedulers racing must
        not silently reassign a placed pod). Binds kubelet or the taint
        manager would reject — untolerated NoSchedule/NoExecute taints,
        unmatched nodeSelector, cpu/mem requests over what's left of the
        node's allocatable — are refused with 422, so a binder that
        skips its own hard filters fails loudly in tier-1 instead of
        placing pods a real cluster would evict."""
        with self.lock:
            pod = self.objects["pods"].get((ns, name))
            if pod is None:
                raise _HttpError(404, "NotFound", f"pod {ns}/{name} not found")
            current = (pod.get("spec") or {}).get("nodeName", "")
            if current:
                raise _HttpError(
                    409, "Conflict",
                    f"pod {ns}/{name} is already assigned to node {current}")
            node_obj = self.objects["nodes"].get(("", node))
            if node_obj is not None:
                reason = self._bind_rejection(pod, node_obj, node)
                if reason:
                    raise _HttpError(
                        422, "Invalid",
                        f"pod {ns}/{name} cannot bind: {reason}")
            self.patch("pods", ns, name, {"spec": {"nodeName": node}})
        return _status_body(201, "Created", f"{name} bound to {node}") | {
            "status": "Success"}

    def _bind_rejection(self, pod_raw: dict, node_raw: dict,
                        node_name: str) -> Optional[str]:
        """Run the binder's own hard predicate over the k8s-shaped
        objects (converted through the production parsers — the fake
        validates the SAME contract the operator filters on, so the two
        cannot drift). Caller holds the lock."""
        from tf_operator_tpu.controller import binder as binder_mod
        from tf_operator_tpu.runtime.kube import node_from_k8s, pod_from_k8s

        pod = pod_from_k8s(pod_raw)
        node = node_from_k8s(node_raw)
        free_cpu = node.status.allocatable_cpu_millis
        free_mem = node.status.allocatable_memory_bytes
        if free_cpu is not None or free_mem is not None:
            for (_, _), other in self.objects["pods"].items():
                spec = other.get("spec") or {}
                if spec.get("nodeName") != node_name:
                    continue
                if ((other.get("status") or {}).get("phase", "")
                        in ("Succeeded", "Failed")):
                    continue
                op = pod_from_k8s(other)
                if free_cpu is not None:
                    free_cpu -= binder_mod.pod_cpu_millis(op)
                if free_mem is not None:
                    free_mem -= binder_mod.pod_memory_bytes(op)
        return binder_mod.node_rejects_pod(pod, node, free_cpu, free_mem)

    def set_all_pods_phase(self, ns: str, phase: str, *,
                           selector: Optional[Dict[str, str]] = None) -> int:
        raw = ",".join(f"{k}={v}" for k, v in (selector or {}).items())
        with self.lock:
            names = [name for (ons, name), obj in self.objects["pods"].items()
                     if ons == ns and _match_selector(
                         (obj.get("metadata") or {}).get("labels") or {},
                         raw)]
        for name in names:
            self.set_pod_phase(ns, name, phase)
        return len(names)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "fake-kube-apiserver"
    state: FakeKubeState

    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    # -- routing -----------------------------------------------------------

    def _route(self):
        """-> (resource, ns_or_None, name, subresource, query)."""
        parsed = urllib.parse.urlsplit(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = {k: v[0] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        # /api/v1/... (core) or /apis/{group}/{version}/... (CRs)
        if parts[:2] == ["api", "v1"]:
            rest = parts[2:]
        elif (parts[:3] == ["apis", constants.GROUP, constants.VERSION]):
            rest = parts[3:]
        elif parts[:3] == ["apis", "coordination.k8s.io", "v1"]:
            rest = parts[3:]
        elif parts[:3] == ["apis", "policy", "v1"]:
            rest = parts[3:]
        elif (parts[:3] == ["apis", "apiextensions.k8s.io", "v1"]
              and parts[3:4] == ["customresourcedefinitions"]):
            # CRD existence probe: report installed.
            name = parts[4] if len(parts) > 4 else ""
            if name and name != constants.CRD_NAME:
                raise _HttpError(404, "NotFound", f"CRD {name} not found")
            return "_crd_probe", None, name, "", query
        else:
            raise _HttpError(404, "NotFound", f"no route {self.path}")
        ns = None
        if rest[:1] == ["namespaces"] and len(rest) >= 3:
            ns = rest[1]
            rest = rest[2:]
        if not rest:
            raise _HttpError(404, "NotFound", f"no route {self.path}")
        resource, rest = rest[0], rest[1:]
        if resource not in RESOURCES:
            raise _HttpError(404, "NotFound", f"unknown resource {resource}")
        name = rest[0] if rest else ""
        sub = rest[1] if len(rest) > 1 else ""
        return resource, ns, name, sub, query

    # -- plumbing ----------------------------------------------------------

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0") or "0")
        raw = self.rfile.read(length) if length else b"{}"
        try:
            return json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            raise _HttpError(400, "Invalid", f"bad JSON: {e}")

    def _request_verb_kind(self) -> Tuple[str, str]:
        """(verb, resource) of the in-flight request, best-effort, for
        FaultProfile rate lookup (routing proper happens later)."""
        parts = [p for p in
                 urllib.parse.urlsplit(self.path).path.split("/") if p]
        resource = next((p for p in parts if p in RESOURCES), "*")
        if "watch=1" in self.path or "watch=true" in self.path:
            return "watch", resource
        verb = {"GET": "get", "POST": "create", "PATCH": "patch",
                "PUT": "update", "DELETE": "delete"}.get(
                    self.command, "get")
        if verb == "get" and parts and parts[-1] in RESOURCES:
            verb = "list"
        return verb, resource

    def _chaos_gate(self) -> bool:
        """Apply injected latency / 429 / 5xx / FaultProfile faults
        before routing. Returns True when the request was consumed by
        an injected error. Watch requests only pay latency
        (stream-level chaos has its own taps in _serve_watch)."""
        import time as _time

        is_watch = "watch=1" in self.path or "watch=true" in self.path
        verb, kind = self._request_verb_kind()
        with self.state.lock:
            delay = self.state.latency_seconds
            status = None
            if not is_watch:
                if self.state.inject_429 > 0:
                    self.state.inject_429 -= 1
                    self.state.throttled_requests += 1
                    status = 429
                elif self.state.inject_5xx > 0:
                    self.state.inject_5xx -= 1
                    status = 500
            retry_after = self.state.retry_after_seconds
            inj = self.state.fault_injector
        if delay:
            _time.sleep(delay)
        if status is None and inj is not None and not is_watch:
            # Seeded probabilistic faults (runtime/chaos.py). Order is
            # meanest-first: a dropped connection beats a clean error
            # body beats a conflict.
            if inj.decide("timeout", verb, kind):
                # No response at all: the client sees a reset/remote-
                # disconnect and cannot know whether the server applied
                # the write — exactly the ambiguity production retries
                # must survive. (The request was consumed BEFORE
                # routing, so nothing was applied here.)
                self.close_connection = True
                return True
            mutating = verb in ("create", "patch", "update", "delete")
            if mutating and inj.decide("conflict", verb, kind) \
                    and verb in ("patch", "update"):
                self._send_json(409, _status_body(
                    409, "Conflict",
                    "injected conflict: the object has been modified"))
                return True
            fault = "write_error" if mutating else "read_error"
            if inj.decide(fault, verb, kind):
                self._send_json(500, _status_body(
                    500, "InternalError", "injected server error"))
                return True
        if status == 429:
            body = json.dumps(_status_body(
                429, "TooManyRequests", "throttled (injected)")).encode()
            self.send_response(429)
            self.send_header("Retry-After", str(retry_after))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return True
        if status == 500:
            self._send_json(500, _status_body(
                500, "InternalError", "injected server error"))
            return True
        return False

    def _guard(self, fn):
        try:
            if self._chaos_gate():
                return
            fn()
        except _HttpError as e:
            try:
                self._send_json(e.code, _status_body(e.code, e.reason,
                                                     e.message))
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        def run():
            resource, ns, name, sub, query = self._route()
            if resource == "_crd_probe":
                self.state.authorize("customresourcedefinitions", "get")
                return self._send_json(200, {
                    "kind": "CustomResourceDefinition",
                    "metadata": {"name": constants.CRD_NAME}})
            if resource == "pods" and name and sub == "log":
                self.state.authorize("pods", "get", subresource="log")
                return self._serve_pod_log(ns or "default", name, query)
            if name:
                self.state.authorize(resource, "get")
                return self._send_json(200, self.state.get(
                    resource, _default_ns(resource, ns), name))
            if query.get("watch") in ("1", "true"):
                self.state.authorize(resource, "watch")
                return self._serve_watch(resource, ns, query)
            self.state.authorize(resource, "list")
            with self.state.lock:
                self.state.list_counts[resource] = \
                    self.state.list_counts.get(resource, 0) + 1
            return self._send_json(200, self.state.list(
                resource, ns, query.get("labelSelector", ""),
                field_selector=query.get("fieldSelector", "")))
        self._guard(run)

    def do_POST(self):
        def run():
            resource, ns, name, sub, _q2 = self._route()
            if resource == "pods" and name and sub == "binding":
                self.state.authorize("pods", "create",
                                     subresource="binding")
                body = self._read_body()
                target = (body.get("target") or {}).get("name", "")
                if not target:
                    raise _HttpError(400, "Invalid", "binding target required")
                return self._send_json(201, self.state.bind_pod(
                    ns or "default", name, target))
            if name:
                raise _HttpError(405, "MethodNotAllowed", "POST to item")
            self.state.authorize(resource, "create")
            self._send_json(201, self.state.create(
                resource, _default_ns(resource, ns), self._read_body()))
        self._guard(run)

    def do_DELETE(self):
        def run():
            resource, ns, name, _, _q2 = self._route()
            if not name:
                raise _HttpError(405, "MethodNotAllowed", "DELETE collection")
            self.state.authorize(resource, "delete")
            self._send_json(200, self.state.delete(
                resource, _default_ns(resource, ns), name))
        self._guard(run)

    def do_PUT(self):
        def run():
            resource, ns, name, _, _q2 = self._route()
            if not name:
                raise _HttpError(405, "MethodNotAllowed", "PUT collection")
            self.state.authorize(resource, "update")
            self._send_json(200, self.state.replace(
                resource, _default_ns(resource, ns), name,
                self._read_body()))
        self._guard(run)

    def do_PATCH(self):
        def run():
            resource, ns, name, sub, _q2 = self._route()
            if not name:
                raise _HttpError(405, "MethodNotAllowed", "PATCH collection")
            # Subresources are distinct RBAC names (tpujobs/status); the
            # status writes of core resources (pods, nodes) are the fake
            # kubelet's own — they arrive through the state helpers, not
            # HTTP, so the role stays exactly what the OPERATOR needs.
            self.state.authorize(resource, "patch",
                                 subresource=sub)
            ctype = self.headers.get("Content-Type", "")
            if "merge-patch" not in ctype and "strategic" not in ctype:
                raise _HttpError(415, "UnsupportedMediaType",
                                 f"unsupported patch type {ctype}")
            self._send_json(200, self.state.patch(
                resource, _default_ns(resource, ns), name,
                self._read_body(), subresource=sub))
        self._guard(run)

    # -- pod logs (kubelet log API subresource) ----------------------------

    def _serve_pod_log(self, ns: str, name: str, query) -> None:
        import time as _time

        self.state.get("pods", ns, name)  # 404 when the pod is gone
        follow = query.get("follow") in ("1", "true")
        if not follow:
            text = self.state.pod_logs.get((ns, name), "")
            tail = query.get("tailLines")
            if tail is not None:
                n = int(tail)
                lines = text.splitlines()[-n:] if n > 0 else []
                text = "\n".join(lines)
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        # follow: stream appended text until the pod reaches a terminal
        # phase (kubectl logs -f semantics).
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Connection", "close")
        self.end_headers()
        pos = 0
        try:
            while True:
                text = self.state.pod_logs.get((ns, name), "")
                if len(text) > pos:
                    chunk = text[pos:].encode()
                    pos = len(text)
                    self.wfile.write(chunk)
                    self.wfile.flush()
                    continue
                try:
                    pod = self.state.get("pods", ns, name)
                except _HttpError:
                    return
                phase = (pod.get("status") or {}).get("phase", "")
                if phase in ("Succeeded", "Failed"):
                    return
                _time.sleep(0.05)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    # -- watch -------------------------------------------------------------

    def _serve_watch(self, resource: str, ns: Optional[str], query) -> None:
        import time as _time

        selector = query.get("labelSelector", "")
        q = self.state.subscribe(resource)
        # Replay every object newer than the client's resourceVersion as
        # ADDED — the subscribe-after-list race means events landing
        # between the client's list and this subscription would otherwise
        # be lost until a relist that never comes. (A real apiserver
        # serves these from its event history.) rv "0" replays all.
        rv = query.get("resourceVersion", "") or "0"
        try:
            rv_num = int(rv)
        except ValueError:
            rv_num = 0
        # Chaos: history compacted past the client's RV -> immediate
        # 410 ("too old resource version"), the etcd-compaction path a
        # real apiserver takes. The client must relist.
        with self.state.lock:
            compacted = bool(rv_num and rv_num < self.state.compact_rv)
        if compacted:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Connection", "close")
            self.end_headers()
            line = json.dumps({"type": "ERROR", "object": {
                "code": 410, "reason": "Expired",
                "message": "too old resource version"}})
            self.wfile.write(line.encode() + b"\n")
            self.wfile.flush()
            return
        for item in self.state.list(resource, ns, selector)["items"]:
            try:
                item_rv = int((item.get("metadata") or {})
                              .get("resourceVersion", "0"))
            except ValueError:
                item_rv = 0
            if item_rv > rv_num or rv_num == 0:
                q.put(("ADDED", item))
        # Honor timeoutSeconds: real watches expire and clients relist,
        # which is also the fake's backstop for window-lost deletions.
        try:
            deadline = _time.monotonic() + float(
                query.get("timeoutSeconds", "300"))
        except ValueError:
            deadline = _time.monotonic() + 300.0
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        held: Optional[tuple] = None  # chaos: event delayed for reorder
        try:
            while _time.monotonic() < deadline:
                try:
                    etype, obj = q.get(timeout=_KEEPALIVE_SECONDS)
                except _q.Empty:
                    self.wfile.write(b"\n")
                    self.wfile.flush()
                    continue
                meta = obj.get("metadata") or {}
                if ns is not None and meta.get("namespace") != ns:
                    continue
                if not _match_selector(meta.get("labels") or {}, selector):
                    continue
                # Chaos taps (see FakeKubeState.__init__): each applies
                # to events that WOULD be delivered, so tests control
                # exactly which update is lost/errored/reordered.
                with self.state.lock:
                    if self.state.drop_events > 0:
                        self.state.drop_events -= 1
                        continue  # silently lost on the wire
                    if self.state.inject_watch_errors > 0:
                        self.state.inject_watch_errors -= 1
                        code = self.state.watch_error_code
                        line = json.dumps({"type": "ERROR", "object": {
                            "code": code, "reason": "Chaos",
                            "message": "injected watch error"}})
                        self.wfile.write(line.encode() + b"\n")
                        self.wfile.flush()
                        return  # stream dies with the error
                    if self.state.reorder_events > 0 and held is None:
                        self.state.reorder_events -= 1
                        held = (etype, obj)
                        continue  # delivered after the NEXT event
                    inj = self.state.fault_injector
                if inj is not None and inj.decide("watch_drop", "watch",
                                                  resource):
                    # Stream dies BEFORE this event is delivered (the
                    # connection-drop analog): the client must
                    # reconnect, and RV-resume replays everything from
                    # its last delivered event — losing nothing iff the
                    # reflector resumes correctly, which is the
                    # property under test.
                    return
                line = json.dumps({"type": etype, "object": obj})
                self.wfile.write(line.encode() + b"\n")
                if held is not None:
                    late = json.dumps({"type": held[0], "object": held[1]})
                    self.wfile.write(late.encode() + b"\n")
                    held = None
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.state.unsubscribe(q)


class FakeKubeApiServer:
    """Serve a FakeKubeState over HTTP on a background thread.

    ``rbac_path`` (default: the checked-in operator ClusterRole) is
    loaded into the state's verb table and enforced on every HTTP
    request; ``rbac_path=None`` serves permissively."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 rbac_path: Optional[str] = DEFAULT_RBAC_PATH):
        self.state = FakeKubeState()
        if rbac_path is not None and os.path.exists(rbac_path):
            try:
                self.state.rbac_rules = load_rbac_rules(rbac_path)
            except Exception:
                log.warning("failed to load RBAC rules from %s; "
                            "serving permissively", rbac_path,
                            exc_info=True)
        handler = type("BoundHandler", (_Handler,), {"state": self.state})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FakeKubeApiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fake-kube", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "FakeKubeApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
