"""Shared notice/checkpoint relay — the contract between the control
plane and the worker process, factored out of the data planes.

Both node planes implement the same loop (SURVEY §3.3's kubelet status
feedback, specialized to checkpoint coordination): a preemption notice
stamped on the pod must reach the worker as a file at
``TPUJOB_PREEMPT_FILE``, and the worker's checkpoint state published at
``TPUJOB_CKPT_FILE`` must flow back into its ``CheckpointRecord`` so
controller/ckpt.py can run save-before-evict barriers and derive
restore steps. ``LocalProcessBackend`` (runtime/local.py) does this for
subprocesses it spawned; ``runtime/nodeagent.py`` does it for pods the
kubelet runs, through a shared relay volume. This module holds the
path derivation, the atomic notice publish, and the checkpoint-file →
CheckpointRecord mirror so the two planes cannot drift.

File paths are keyed by the pod's relay token — the controller-stamped
``tpu-operator.dev/relay-token`` annotation when present, else the pod
uid. Either way the key is per-incarnation: a restart-with-identity
(same name, new pod) must never read the dead incarnation's notice and
"ack" a barrier it never saved under. The token exists because on kube
the file path is rendered into container env at pod-create time, before
the apiserver assigns a uid.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional, Tuple

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    CheckpointRecord,
    CheckpointRecordStatus,
    ObjectMeta,
    Pod,
)
from tf_operator_tpu.runtime import store as store_mod

log = logging.getLogger("tpu_operator.relay")


def pod_token(pod: Pod) -> str:
    """Per-incarnation file key: the controller's relay token when
    stamped, else the first 8 uid chars (the local backend's historical
    scheme — paths there are unchanged by the token's existence)."""
    token = pod.metadata.annotations.get(constants.ANNOTATION_RELAY_TOKEN, "")
    if token:
        return token
    return (pod.metadata.uid or "nouid")[:8]


def preempt_path(base_dir: str, pod: Pod) -> str:
    """Where this pod's worker process finds a preemption notice."""
    return os.path.join(
        base_dir,
        f"{pod.metadata.namespace}.{pod.metadata.name}.{pod_token(pod)}"
        ".preempt.json")


def ckpt_path(base_dir: str, pod: Pod) -> str:
    """Where this pod's worker process publishes checkpoint state
    (saves / barrier acks / restore confirmation)."""
    return os.path.join(
        base_dir,
        f"{pod.metadata.namespace}.{pod.metadata.name}.{pod_token(pod)}"
        ".ckpt.json")


def forward_notice(base_dir: str, pod: Pod, notice: str,
                   last_written: str) -> str:
    """Atomically publish the pod's preemption notice to its notice
    file (the training loop polls it each step). Returns the new
    dedup marker — callers persist it per pod so each barrier's notice
    hits the file once. Raises ``OSError`` on write failure; callers
    retry on the next event/poll."""
    if not notice or last_written == notice:
        return last_written
    path = preempt_path(base_dir, pod)
    os.makedirs(base_dir, exist_ok=True)
    with open(path + ".tmp", "w") as f:
        f.write(notice)
    os.replace(path + ".tmp", path)
    log.info("preemption notice forwarded to pod %s/%s",
             pod.metadata.namespace, pod.metadata.name)
    return notice


def read_ckpt_file(path: str,
                   last_mtime: int) -> Tuple[Optional[dict], int]:
    """Read the worker's checkpoint file if it changed since
    ``last_mtime`` (st_mtime_ns). Returns ``(data, new_mtime)``; data
    is None when the file is absent, unchanged, or partially written
    (the next poll retries — mtime only advances on a full parse)."""
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None, last_mtime
    if mtime == last_mtime:
        return None, last_mtime
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None, last_mtime
    if not isinstance(data, dict):
        return None, last_mtime
    return data, mtime


def ckpt_status_from_data(data: dict, now) -> CheckpointRecordStatus:
    """Convert a worker checkpoint-file payload (or its annotation
    mirror) into a CheckpointRecordStatus."""
    restored = data.get("restored_from_step")
    return CheckpointRecordStatus(
        step=int(data.get("step", -1)),
        progress_step=int(data.get("progress_step", data.get("step", -1))),
        barrier_id=str(data.get("barrier", "")),
        directory=str(data.get("directory", "")),
        save_seconds=float(data.get("save_seconds", 0.0)),
        restored_from_step=(int(restored) if restored is not None else None),
        updated_at=now)


def upsert_checkpoint_record(store, pod: Pod, data: dict, now) -> bool:
    """Mirror a worker checkpoint payload into the pod's
    CheckpointRecord (create-or-update-status, named after the pod,
    labeled/owned like it). Returns False on a store race — the caller
    resets its mtime/dedup marker so the next tick re-mirrors."""
    status = ckpt_status_from_data(data, now)
    ns, name = pod.metadata.namespace, pod.metadata.name
    try:
        existing = store.try_get(store_mod.CHECKPOINTRECORDS, ns, name)
        if existing is None:
            record = CheckpointRecord(
                metadata=ObjectMeta(
                    name=name, namespace=ns,
                    labels={k: v for k, v in pod.metadata.labels.items()
                            if k in (constants.LABEL_JOB_NAME,
                                     constants.LABEL_REPLICA_TYPE,
                                     constants.LABEL_REPLICA_INDEX)},
                    owner_references=[r.deepcopy() for r in
                                      pod.metadata.owner_references]),
                status=status)
            store.create(store_mod.CHECKPOINTRECORDS, record)
        else:
            existing.status = status
            store.update_status(store_mod.CHECKPOINTRECORDS, existing)
    except (store_mod.AlreadyExistsError, store_mod.ConflictError,
            store_mod.NotFoundError):
        return False
    return True


def cleanup(base_dir: str, pod: Pod) -> None:
    """Remove the pod's relay files — retention follows the pod object
    (kubelet log-retention semantics)."""
    for path in (preempt_path(base_dir, pod), ckpt_path(base_dir, pod)):
        try:
            os.unlink(path)
        except OSError:
            pass
