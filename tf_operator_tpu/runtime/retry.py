"""Shared retry/backoff + degraded-mode machinery for the control plane.

Before this module every subsystem had its own one-off answer to a
flaky API server: the engine raised and leaned on the workqueue's
rate-limited requeue, gang eviction logged-and-hoped for the next pass,
health cordons warned and returned, quota/ckpt status writes silently
dropped conflicts, and the remote client surfaced every 5xx straight to
its caller. Under a real 429/500 storm those behaviors compose into
exactly the failure modes chaos testing exists to catch: half-executed
drains, barrier notices stamped but never enforced, and retry storms
with no cap. This module centralizes the three primitives they all
need (client-go's retry.OnError / RetryOnConflict / flowcontrol
backoff, collapsed to what this codebase uses):

- ``with_retries``: capped exponential backoff with FULL jitter,
  deadline-aware, retrying only classified-transient failures
  (``is_transient``); attempts are counted in
  ``tpu_operator_api_retries_total{component}`` and reported into an
  optional ``ControlPlaneHealth`` so repeated failure trips degraded
  mode.
- ``update_with_conflict_retry``: conflict-aware read-modify-write for
  status/annotation writes — re-read, re-apply the mutation, re-write,
  bounded; the client-go ``RetryOnConflict`` shape that every
  optimistic-concurrency write site here used to approximate (or skip).
- ``ControlPlaneHealth``: reachability tracker. While the API server
  has been failing past a threshold the controller is DEGRADED: it
  keeps reconciling (level-triggered reads and creates retry harmlessly)
  but stops *initiating* disruptive actions — slice drains, quota
  reclaims, priority preemptions — because a half-executed eviction
  against an unreachable apiserver is how gangs end up drained but
  never rebound and barriers end up stamped but unenforced. State is
  surfaced via the ``tpu_operator_controlplane_degraded`` gauge, a
  ``ControlPlaneDegraded`` job condition (engine.py), and per-action
  ``tpu_operator_disruptions_deferred_total``.

Fault classification: NotFound / Conflict / AlreadyExists are SEMANTIC
outcomes every caller here already handles (level-triggered deletes,
CAS losses, create races) — never retried by ``with_retries``.
Transient is 5xx/429-class server errors (anything carrying an integer
``.code`` >= 500 or == 429, which covers both ``KubeApiError`` and the
fault injector's ``TransientAPIError``), timeouts, and dropped
connections.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime import trace as trace_mod

log = logging.getLogger("tpu_operator.retry")


class TransientAPIError(Exception):
    """A retryable control-plane failure (5xx-class blip, timeout,
    dropped connection). Carries ``code`` so classification by status
    code and by type agree."""

    def __init__(self, message: str = "transient API error",
                 code: int = 500):
        super().__init__(message)
        self.code = code


def is_transient(exc: BaseException) -> bool:
    """Whether a failure is worth retrying in place. Semantic outcomes
    (NotFound/Conflict/AlreadyExists) are not — their callers handle
    them; everything that smells like an infrastructure blip is."""
    if isinstance(exc, (store_mod.NotFoundError, store_mod.ConflictError,
                        store_mod.AlreadyExistsError)):
        return False
    code = getattr(exc, "code", None)
    if isinstance(code, int) and code:
        return code == 429 or code >= 500
    return isinstance(exc, (TransientAPIError, TimeoutError,
                            ConnectionError, OSError))


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter (AWS-style: sleep a
    uniform draw from [0, min(cap, base * 2^attempt)] — restarted
    retriers never thundering-herd) plus an overall deadline."""

    base_delay: float = 0.05
    max_delay: float = 2.0
    max_attempts: int = 4          # total tries = max_attempts
    deadline_seconds: Optional[float] = None

    def delay(self, attempt: int, rng: Callable[[], float]) -> float:
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return cap * rng()


#: Short in-place policy for per-object writes inside a reconcile pass.
#: Deliberately small: the workqueue's rate-limited requeue is the
#: long-haul retry loop; this only absorbs blips so a single 500 does
#: not abort a whole sync.
DEFAULT_POLICY = RetryPolicy()

#: Standalone-client policy (SDK / remote store): no outer workqueue to
#: lean on, so it tries longer before surfacing.
CLIENT_POLICY = RetryPolicy(base_delay=0.1, max_delay=5.0,
                            max_attempts=5, deadline_seconds=30.0)


def with_retries(fn: Callable[[], object], *,
                 policy: RetryPolicy = DEFAULT_POLICY,
                 component: str = "",
                 retryable: Callable[[BaseException], bool] = is_transient,
                 health: Optional["ControlPlaneHealth"] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] = random.random):
    """Call ``fn``; on a retryable failure back off and try again until
    attempts or the deadline run out, then re-raise the last error.
    Success/failure outcomes feed ``health`` (degraded-mode tracking)
    and retries are counted per ``component``. Inside a traced sync
    the whole call is a child span carrying its attempt count, and
    backoff sleeps are attributed to the ``api_retry`` phase — retry
    and conflict loops show up in the timeline instead of vanishing
    into ``api_retries_total`` (runtime/trace.py)."""
    with trace_mod.span(f"retry.{component or 'unknown'}") as sp:
        deadline = (time.monotonic() + policy.deadline_seconds
                    if policy.deadline_seconds is not None else None)
        last: Optional[BaseException] = None
        for attempt in range(policy.max_attempts):
            try:
                result = fn()
            except BaseException as e:  # classified below; re-raised verbatim
                if not retryable(e):
                    sp.set(attempts=attempt + 1)
                    raise
                last = e
                if health is not None:
                    health.record_failure()
                if attempt + 1 >= policy.max_attempts:
                    break
                delay = policy.delay(attempt, rng)
                if deadline is not None and time.monotonic() + delay > deadline:
                    break
                metrics.api_retries.inc(component=component or "unknown")
                log.debug("%s: transient failure (attempt %d/%d), retrying "
                          "in %.3fs: %s", component or fn, attempt + 1,
                          policy.max_attempts, delay, e)
                trace_mod.note_phase("api_retry", delay)
                sleep(delay)
                continue
            if health is not None:
                health.record_success()
            sp.set(attempts=attempt + 1)
            return result
        assert last is not None
        sp.set(attempts=policy.max_attempts, exhausted=True)
        raise last


def update_with_conflict_retry(store, kind: str, namespace: str,
                               name: str,
                               mutate: Callable[[object], Optional[bool]],
                               *, status: bool = False,
                               attempts: int = 4,
                               component: str = ""):
    """Conflict-aware read-modify-write (client-go RetryOnConflict):
    fetch the CURRENT object, apply ``mutate`` (return False to abort —
    the precondition no longer holds), write it back; a ConflictError
    re-reads and re-applies so the mutation always lands on fresh state
    instead of silently losing to a racing writer. Returns the written
    object, or None when the object vanished / ``mutate`` aborted /
    attempts ran out."""
    with trace_mod.span(f"retry.{component or 'conflict'}") as sp:
        for attempt in range(attempts):
            obj = store.try_get(kind, namespace, name)
            if obj is None:
                return None
            if mutate(obj) is False:
                return None
            try:
                sp.set(attempts=attempt + 1)
                if status:
                    return store.update_status(kind, obj)
                return store.update(kind, obj)
            except store_mod.ConflictError:
                if attempt + 1 < attempts:
                    metrics.api_retries.inc(component=component or "conflict")
                continue
            except store_mod.NotFoundError:
                return None
        sp.set(exhausted=True)
        return None


class ControlPlaneHealth:
    """API-server reachability tracker + disruptive-action gate.

    ``record_failure``/``record_success`` are fed by the retry wrapper
    (and may be called directly). The controller is DEGRADED once
    failures have been continuous for ``threshold_seconds`` AND at
    least ``failure_threshold`` consecutive calls failed — a single
    blip never trips it, a dead apiserver always does. One success
    clears it (the K8s liveness convention: reachability is now, not
    history).

    ``allow_disruption(action)`` is the gate eviction-initiating code
    paths consult: True = proceed; False = the control plane is
    degraded, defer (counted per action, logged once per episode). The
    point is invariant protection, not availability: a drain or reclaim
    started against an unreachable apiserver half-executes — pods
    deleted but the gang never displaced, a barrier stamped but its
    eviction never enforced — and those are exactly the states the
    chaos invariants forbid."""

    def __init__(self, threshold_seconds: float = 10.0,
                 failure_threshold: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold_seconds = threshold_seconds
        self.failure_threshold = failure_threshold
        self.clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._failing_since: Optional[float] = None
        self._degraded = False
        self._deferral_logged: set = set()

    def record_failure(self) -> None:
        with self._lock:
            now = self.clock()
            self._consecutive_failures += 1
            if self._failing_since is None:
                self._failing_since = now
            if (not self._degraded
                    and self._consecutive_failures >= self.failure_threshold
                    and now - self._failing_since >= self.threshold_seconds):
                self._degraded = True
                metrics.controlplane_degraded.set(1)
                metrics.degraded_entries.inc()
                log.error(
                    "control plane DEGRADED: %d consecutive API failures "
                    "over %.1fs — deferring new drains/reclaims/"
                    "preemptions until the API server answers again",
                    self._consecutive_failures, now - self._failing_since)

    def record_success(self) -> None:
        with self._lock:
            was = self._degraded
            self._consecutive_failures = 0
            self._failing_since = None
            self._degraded = False
            self._deferral_logged.clear()
        if was:
            metrics.controlplane_degraded.set(0)
            log.warning("control plane recovered; resuming disruptive "
                        "actions")

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def allow_disruption(self, action: str) -> bool:
        """Gate for eviction-INITIATING paths (drain, reclaim,
        preemption). Completing an already-started eviction is never
        gated — leaving a victim half-evicted is the worse state."""
        with self._lock:
            if not self._degraded:
                return True
            first = action not in self._deferral_logged
            self._deferral_logged.add(action)
        metrics.disruptions_deferred.inc(action=action)
        if first:
            log.warning("control plane degraded: deferring %s until the "
                        "API server recovers", action)
        return False
