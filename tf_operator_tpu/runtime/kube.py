"""Kubernetes cluster backend: the operator against a real API server.

Reference parity:

- config resolution + clientsets: cmd/tf-operator.v1/app/server.go:72-229
  (kubeconfig / in-cluster, five clientsets) — here one stdlib REST client.
- RealPodControl / RealServiceControl: vendor/.../control/pod_control.go:66+,
  service_control.go (create/delete with controller ownerRefs + events).
- Informer list+watch feeding the controller's cache: the generated
  informer factory (pkg/client/informers/) + unstructured TFJob informer
  (pkg/controller.v1/tensorflow/informer.go:33-53).
- Adoption ownership patch: controller_ref_manager.go:208-221.
- Status writes via the CRD status subresource: tensorflow/status.go:222-240.

Design: the reconcile engine is unchanged. The in-process ``Store`` plays
the informer-cache role: ``KubeInformer`` threads list+watch TPUJob CRs,
Pods, and Services from the cluster and mirror them into the Store (which
fires the controller's existing watch handlers, driving expectations and
the workqueue exactly as in the local runtime). The write path —
``KubePodControl``/``KubeEndpointControl``, status patches, adoption
patches — goes to the API server, and the resulting watch events close
the loop: API write -> watch -> cache -> expectation observed.

Everything here is stdlib (urllib + ssl + json; yaml only to parse
kubeconfig): the runtime image carries no kubernetes client package, and
the API subset the engine needs is small and stable.
"""

from __future__ import annotations

import atexit
import base64
import dataclasses
import json
import logging
import os
import random
import socket
import ssl
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.serde import parse_time
from tf_operator_tpu.api.types import (
    Container,
    ContainerStatus,
    Endpoint,
    EndpointSpec,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
    TPUJob,
)
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.events import (
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    Recorder,
)
from tf_operator_tpu.runtime.store import Store

log = logging.getLogger("tpu_operator.kube")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Client-only resource kind (not mirrored in the Store): PDBs exist to
# inform the CLUSTER's eviction machinery, nothing reconciles off them.
KIND_PDBS = "poddisruptionbudgets"

# Key-material temp files materialized from inline kubeconfig data;
# removed at exit so credentials never persist in the tempdir.
_TEMP_KEY_FILES: list = []


@atexit.register
def _cleanup_temp_key_files() -> None:
    for path in _TEMP_KEY_FILES:
        try:
            os.unlink(path)
        except OSError:
            pass

# Restart policies core/v1 Pods accept; the engine maps ExitCode -> Never
# before the control sees the pod (reference setRestartPolicy,
# tensorflow/pod.go:319-326), this is the defensive backstop.
_K8S_RESTART_POLICIES = ("Always", "OnFailure", "Never")


class KubeApiError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        super().__init__(f"{reason} ({code}): {message}")
        self.code = code
        self.reason = reason
        self.message = message


# ---------------------------------------------------------------------------
# Config resolution (reference app/server.go:96-111 BuildConfigFromFlags)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class KubeConfig:
    server: str = ""
    token: str = ""
    ca_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    verify: bool = True
    namespace: str = "default"
    # Temp files holding key material materialized from inline
    # kubeconfig *-data fields — deleted by close() and, as a backstop,
    # at interpreter exit (key material must not outlive the process in
    # the tempdir).
    temp_key_files: Tuple[str, ...] = ()

    def close(self) -> None:
        for path in self.temp_key_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        object.__setattr__(self, "temp_key_files", ())

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Service-account config inside a pod (reference rest.InClusterConfig
        via BuildConfigFromFlags with empty kubeconfig)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise KubeApiError(0, "NoCluster",
                               "KUBERNETES_SERVICE_HOST not set")
        with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as f:
            token = f.read().strip()
        ns_path = os.path.join(SERVICE_ACCOUNT_DIR, "namespace")
        namespace = "default"
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                namespace = f.read().strip() or "default"
        return cls(server=f"https://{host}:{port}", token=token,
                   ca_file=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
                   namespace=namespace)

    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None,
                        context: Optional[str] = None) -> "KubeConfig":
        """Parse a kubeconfig file (reference clientcmd loading rules)."""
        import yaml

        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
            "~/.kube/config")
        with open(path) as f:
            doc = yaml.safe_load(f) or {}

        def _by_name(section: str, name: str) -> dict:
            for entry in doc.get(section, []) or []:
                if entry.get("name") == name:
                    return entry
            raise KubeApiError(0, "BadKubeconfig",
                               f"{section} entry {name!r} not found in {path}")

        ctx_name = context or doc.get("current-context", "")
        if not ctx_name:
            raise KubeApiError(0, "BadKubeconfig",
                               f"no current-context in {path}")
        ctx = _by_name("contexts", ctx_name).get("context", {})
        cluster = _by_name("clusters", ctx.get("cluster", "")).get("cluster", {})
        user = _by_name("users", ctx.get("user", "")).get("user", {})

        materialized: list = []

        def _materialize(data_key: str, file_key: str, src: dict) -> str:
            """Inline base64 *-data fields become temp files for ssl
            (mkstemp => 0600). Paths are tracked for KubeConfig.close()
            and deleted at interpreter exit as a backstop — key material
            must not be left behind in the tempdir."""
            if src.get(file_key):
                return src[file_key]
            data = src.get(data_key)
            if not data:
                return ""
            fd, tmp = tempfile.mkstemp(prefix="kubecfg-", suffix=".pem")
            with os.fdopen(fd, "wb") as f:
                f.write(base64.b64decode(data))
            materialized.append(tmp)
            _TEMP_KEY_FILES.append(tmp)
            return tmp

        return cls(
            server=cluster.get("server", ""),
            token=user.get("token", ""),
            ca_file=_materialize("certificate-authority-data",
                                 "certificate-authority", cluster),
            client_cert_file=_materialize("client-certificate-data",
                                          "client-certificate", user),
            client_key_file=_materialize("client-key-data", "client-key",
                                         user),
            verify=not cluster.get("insecure-skip-tls-verify", False),
            namespace=ctx.get("namespace", "default"),
            temp_key_files=tuple(materialized),
        )

    @classmethod
    def resolve(cls, kubeconfig: Optional[str] = None) -> "KubeConfig":
        """In-cluster when running inside a pod, else kubeconfig —
        the reference's loading order (server.go:96-103)."""
        if not kubeconfig and os.environ.get("KUBERNETES_SERVICE_HOST"):
            return cls.in_cluster()
        return cls.from_kubeconfig(kubeconfig)


# ---------------------------------------------------------------------------
# REST client
# ---------------------------------------------------------------------------

def _selector_str(selector: Optional[Dict[str, str]]) -> str:
    if not selector:
        return ""
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


class _TokenBucket:
    """Client-side request rate limiter (reference flags --kube-api-qps 5
    / --kube-api-burst 10, options.go:81-82; client-go's flowcontrol
    token bucket). acquire() blocks until a token is available — a hot
    requeue loop smooths out instead of hammering the API server."""

    def __init__(self, qps: float, burst: int):
        if qps <= 0:
            raise ValueError("qps must be positive")
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(self.burst,
                                   self._tokens + (now - self._last)
                                   * self.qps)
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


# 429 handling: how many Retry-After waits one request will sit out
# before surfacing the error, and the per-wait cap (a malicious/buggy
# Retry-After of hours must not hang a reconcile worker).
_MAX_429_RETRIES = 5
_MAX_RETRY_AFTER_SECONDS = 30.0


class KubeClient:
    """Minimal typed REST client over the K8s API (stdlib only).

    ``qps``/``burst`` enable the client-side token bucket (None =
    unlimited — library default; the operator binary passes the
    reference's 5/10). Server 429s are honored: the client sleeps the
    Retry-After (capped) and retries a few times before surfacing."""

    def __init__(self, config: KubeConfig, timeout: float = 30.0,
                 watch_timeout_seconds: float = 300.0,
                 qps: Optional[float] = None, burst: int = 10):
        self.config = config
        self.timeout = timeout
        # Server-side watch expiry; a stream that outlives it ends
        # normally and the reflector RESUMES from its last RV (tests
        # shorten this to exercise the resume path).
        self.watch_timeout_seconds = watch_timeout_seconds
        self._bucket = _TokenBucket(qps, burst) if qps else None
        self._ssl: Optional[ssl.SSLContext] = None
        if config.server.startswith("https"):
            ctx = ssl.create_default_context(
                cafile=config.ca_file or None)
            if config.client_cert_file:
                ctx.load_cert_chain(config.client_cert_file,
                                    config.client_key_file or None)
            if not config.verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl = ctx

    # -- plumbing ----------------------------------------------------------

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[Dict[str, str]] = None,
                content_type: str = "application/json",
                timeout: Optional[float] = None,
                stream: bool = False):
        url = self.config.server.rstrip("/") + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v not in ("", None)})
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_429_RETRIES + 1):
            if self._bucket is not None:
                self._bucket.acquire()
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Accept", "application/json")
            if data is not None:
                req.add_header("Content-Type", content_type)
            if self.config.token:
                req.add_header("Authorization",
                               f"Bearer {self.config.token}")
            try:
                resp = urllib.request.urlopen(
                    req,
                    timeout=self.timeout if timeout is None else timeout,
                    context=self._ssl)
            except urllib.error.HTTPError as e:
                raw = e.read()
                try:
                    status = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    status = {}
                reason = status.get("reason", "") or e.reason
                message = status.get("message", "") or raw.decode(
                    "utf-8", "replace")
                if e.code == 429 and attempt < _MAX_429_RETRIES:
                    # Server throttling: honor Retry-After (capped) and
                    # go again — client-go's standard 429 behavior.
                    try:
                        after = float(e.headers.get("Retry-After", "1")
                                      or "1")
                    except ValueError:
                        after = 1.0
                    metrics.kube_client_throttled.inc()
                    time.sleep(min(max(after, 0.0),
                                   _MAX_RETRY_AFTER_SECONDS))
                    continue
                if e.code == 404:
                    raise store_mod.NotFoundError(message)
                if e.code == 409 and reason == "AlreadyExists":
                    raise store_mod.AlreadyExistsError(message)
                if e.code == 409:
                    raise store_mod.ConflictError(message)
                raise KubeApiError(e.code, reason, message)
            if stream:
                return resp
            with resp:
                raw = resp.read()
            return json.loads(raw) if raw else {}

    # -- path builders -----------------------------------------------------

    @staticmethod
    def _core(resource: str, ns: Optional[str], name: str = "") -> str:
        base = (f"/api/v1/namespaces/{ns}/{resource}" if ns
                else f"/api/v1/{resource}")
        return f"{base}/{name}" if name else base

    @staticmethod
    def _crd(ns: Optional[str], name: str = "") -> str:
        group = f"/apis/{constants.GROUP}/{constants.VERSION}"
        base = (f"{group}/namespaces/{ns}/{constants.PLURAL}" if ns
                else f"{group}/{constants.PLURAL}")
        return f"{base}/{name}" if name else base

    def _path(self, kind: str, ns: Optional[str], name: str = "") -> str:
        if kind == store_mod.TPUJOBS:
            return self._crd(ns, name)
        if kind == KIND_PDBS:
            base = f"/apis/policy/v1/namespaces/{ns}/poddisruptionbudgets"
            return f"{base}/{name}" if name else base
        if kind == store_mod.NODES:
            return self._core("nodes", None, name)  # cluster-scoped
        resource = {store_mod.PODS: "pods",
                    store_mod.ENDPOINTS: "services",
                    store_mod.EVENTS: "events"}.get(kind)
        if resource is None:
            raise KeyError(f"no K8s resource mapping for kind {kind!r}")
        return self._core(resource, ns, name)

    # -- typed verbs -------------------------------------------------------

    def create(self, kind: str, ns: str, body: dict) -> dict:
        return self.request("POST", self._path(kind, ns), body=body)

    def get(self, kind: str, ns: str, name: str) -> dict:
        return self.request("GET", self._path(kind, ns, name))

    def delete(self, kind: str, ns: str, name: str) -> dict:
        return self.request("DELETE", self._path(kind, ns, name))

    def list(self, kind: str, ns: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None,
             field_selector: str = "") -> dict:
        return self.request("GET", self._path(kind, ns),
                            params={"labelSelector": _selector_str(selector),
                                    "fieldSelector": field_selector})

    def patch(self, kind: str, ns: str, name: str, patch: dict,
              subresource: str = "") -> dict:
        path = self._path(kind, ns, name)
        if subresource:
            path += f"/{subresource}"
        return self.request("PATCH", path, body=patch,
                            content_type="application/merge-patch+json")

    def create_event(self, ns: str, body: dict) -> dict:
        return self.request("POST", self._core("events", ns), body=body)

    def bind_pod(self, ns: str, name: str, node: str) -> dict:
        """POST a Binding (the scheduler's pods/binding subresource write
        — what kube-scheduler itself calls to place a pod). A 409 means
        another binder won the race; callers treat it as settled."""
        body = {"apiVersion": "v1", "kind": "Binding",
                "metadata": {"name": name, "namespace": ns},
                "target": {"apiVersion": "v1", "kind": "Node",
                           "name": node}}
        return self.request(
            "POST", f"/api/v1/namespaces/{ns}/pods/{name}/binding",
            body=body)

    def watch(self, kind: str, ns: Optional[str],
              selector: Optional[Dict[str, str]],
              resource_version: str,
              resp_box: Optional[list] = None):
        """Open a watch stream; yields (type, raw_object) tuples until the
        server closes the connection (callers reconnect; reference
        ListWatch + reflector relist semantics). ``resp_box`` receives the
        live response object so the caller can close it to abort a
        blocking read (informer shutdown)."""
        params = {"watch": "1",
                  "labelSelector": _selector_str(selector),
                  "allowWatchBookmarks": "true",
                  "timeoutSeconds": str(int(self.watch_timeout_seconds)),
                  "resourceVersion": resource_version}
        resp = self.request("GET", self._path(kind, ns), params=params,
                            timeout=self.watch_timeout_seconds + 30.0,
                            stream=True)
        if resp_box is not None:
            resp_box.clear()
            resp_box.append(resp)
        try:
            for line in resp:
                line = line.strip()
                if not line:
                    continue  # keepalive (fake apiserver liveness blanks)
                event = json.loads(line)
                yield event.get("type", ""), event.get("object", {})
        finally:
            resp.close()


# ---------------------------------------------------------------------------
# Wire translation: framework dataclasses <-> core/v1 + CRD objects
# ---------------------------------------------------------------------------

def _meta_to_k8s(meta: ObjectMeta) -> dict:
    out: dict = {"name": meta.name, "namespace": meta.namespace}
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.owner_references:
        out["ownerReferences"] = [r.to_dict() for r in meta.owner_references]
    return out


def _meta_from_k8s(d: dict) -> ObjectMeta:
    # resourceVersion is contractually an OPAQUE string (K8s API
    # conventions): preserved verbatim — int coercion would silently
    # collapse non-numeric RVs to 0 and defeat every CAS that compares
    # them. The local Store issues its own int RVs; equality checks are
    # the only comparison either kind ever participates in.
    rv = str(d.get("resourceVersion", "") or "") or 0
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid", ""),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        creation_timestamp=parse_time(d.get("creationTimestamp")),
        deletion_timestamp=parse_time(d.get("deletionTimestamp")),
        resource_version=rv,
        owner_references=[OwnerReference.from_dict(r)
                          for r in d.get("ownerReferences") or []],
    )


def k8s_resource_version(d: dict) -> str:
    return str((d.get("metadata") or {}).get("resourceVersion", "") or "")


RELAY_VOLUME_NAME = "tpu-operator-relay"


def pod_to_k8s(pod: Pod) -> dict:
    containers = []
    for c in pod.spec.containers:
        kc: dict = {"name": c.name}
        if c.image:
            kc["image"] = c.image
        if c.command:
            kc["command"] = list(c.command)
        if c.args:
            kc["args"] = list(c.args)
        if c.working_dir:
            kc["workingDir"] = c.working_dir
        if c.env:
            kc["env"] = [{"name": k, "value": str(v)}
                         for k, v in sorted(c.env.items())]
        if c.ports:
            kc["ports"] = [{"name": n, "containerPort": int(p)}
                           for n, p in sorted(c.ports.items())]
        if c.resources:
            # Flat resource map -> limits (covers google.com/tpu chip
            # requests; K8s defaults requests from limits).
            kc["resources"] = {"limits": dict(c.resources)}
        if pod.spec.relay_dir:
            # The node-agent relay volume, mounted at the SAME path the
            # agent sees on the host so the TPUJOB_*_FILE env renders
            # one path valid in both mount namespaces.
            kc["volumeMounts"] = [{"name": RELAY_VOLUME_NAME,
                                   "mountPath": pod.spec.relay_dir}]
        containers.append(kc)
    restart = pod.spec.restart_policy
    if restart not in _K8S_RESTART_POLICIES:
        restart = "Never"
    spec: dict = {"containers": containers, "restartPolicy": restart}
    if pod.spec.relay_dir:
        spec["volumes"] = [{"name": RELAY_VOLUME_NAME,
                            "hostPath": {"path": pod.spec.relay_dir,
                                         "type": "DirectoryOrCreate"}}]
    if pod.spec.scheduler_name:
        spec["schedulerName"] = pod.spec.scheduler_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.tolerations:
        # Tolerations ride to the cluster verbatim — the google.com/tpu
        # one stamped on gang workers (tpu_controller.set_cluster_spec)
        # is what keeps GKE's TPU-nodepool taint manager off bound pods.
        spec["tolerations"] = [
            {k: v for k, v in (
                ("key", t.key), ("operator", t.operator),
                ("value", t.value), ("effect", t.effect),
                ("tolerationSeconds", t.toleration_seconds)) if v}
            for t in pod.spec.tolerations]
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": _meta_to_k8s(pod.metadata), "spec": spec}


def _container_from_k8s(kc: dict) -> Container:
    env = {e["name"]: e.get("value", "")
           for e in kc.get("env") or [] if "name" in e}
    ports = {p.get("name", f"port-{p.get('containerPort')}"):
             int(p.get("containerPort", 0)) for p in kc.get("ports") or []}
    resources = dict((kc.get("resources") or {}).get("limits") or {})
    return Container(name=kc.get("name", ""), image=kc.get("image", ""),
                     command=list(kc.get("command") or []),
                     args=list(kc.get("args") or []),
                     env=env, ports=ports,
                     resources={k: str(v) for k, v in resources.items()},
                     working_dir=kc.get("workingDir", ""))


def _container_status_from_k8s(cs: dict) -> ContainerStatus:
    state = cs.get("state") or {}
    mapped, exit_code, message = "", None, ""
    if "terminated" in state:
        mapped = "Terminated"
        exit_code = state["terminated"].get("exitCode")
        message = (state["terminated"].get("message")
                   or state["terminated"].get("reason") or "")
    elif "running" in state:
        mapped = "Running"
    elif "waiting" in state:
        mapped = "Waiting"
        message = (state["waiting"].get("message")
                   or state["waiting"].get("reason") or "")
    return ContainerStatus(name=cs.get("name", ""), state=mapped,
                           exit_code=exit_code,
                           restart_count=int(cs.get("restartCount", 0)),
                           message=message)


def pod_from_k8s(d: dict) -> Pod:
    spec_d = d.get("spec") or {}
    status_d = d.get("status") or {}
    relay_dir = ""
    for vol in spec_d.get("volumes") or []:
        if vol.get("name") == RELAY_VOLUME_NAME:
            relay_dir = (vol.get("hostPath") or {}).get("path", "")
            break
    spec = PodSpec(
        containers=[_container_from_k8s(kc)
                    for kc in spec_d.get("containers") or []],
        restart_policy=spec_d.get("restartPolicy", "Never"),
        scheduler_name=spec_d.get("schedulerName", ""),
        node_selector=dict(spec_d.get("nodeSelector") or {}),
        tolerations=[Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Exists"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
            toleration_seconds=t.get("tolerationSeconds"))
            for t in spec_d.get("tolerations") or []],
        node_name=spec_d.get("nodeName", ""),
        relay_dir=relay_dir,
    )
    status = PodStatus(
        phase=status_d.get("phase", "Pending"),
        container_statuses=[_container_status_from_k8s(cs) for cs in
                            status_d.get("containerStatuses") or []],
        start_time=parse_time(status_d.get("startTime")),
        host=status_d.get("podIP") or status_d.get("hostIP") or "",
        message=status_d.get("message", ""),
    )
    return Pod(metadata=_meta_from_k8s(d.get("metadata") or {}),
               spec=spec, status=status)


def service_to_k8s(ep: Endpoint) -> dict:
    """Per-replica headless Service (reference CreateNewService,
    common/service.go:277-339: ClusterIP None, selector = that one pod)."""
    ports = [{"name": n, "port": int(p)}
             for n, p in sorted(ep.spec.ports.items())]
    if not ports:
        ports = [{"name": constants.DEFAULT_PORT_NAME,
                  "port": constants.DEFAULT_PORT}]
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": _meta_to_k8s(ep.metadata),
            "spec": {"clusterIP": "None",
                     "selector": dict(ep.spec.selector),
                     "ports": ports}}


def endpoint_from_k8s_service(d: dict) -> Endpoint:
    spec_d = d.get("spec") or {}
    ports = {p.get("name", f"port-{p.get('port')}"): int(p.get("port", 0))
             for p in spec_d.get("ports") or []}
    return Endpoint(metadata=_meta_from_k8s(d.get("metadata") or {}),
                    spec=EndpointSpec(selector=dict(spec_d.get("selector")
                                                    or {}),
                                      ports=ports))


def tpujob_to_k8s(job: TPUJob) -> dict:
    d = job.to_dict()
    d["apiVersion"] = constants.API_VERSION
    d["kind"] = constants.KIND
    d["metadata"] = _meta_to_k8s(job.metadata)
    return d


def tpujob_from_k8s(d: dict) -> TPUJob:
    body = dict(d)
    meta = _meta_from_k8s(d.get("metadata") or {})
    body.pop("metadata", None)
    job = TPUJob.from_dict(body)
    job.metadata = meta
    return job


def node_from_k8s(d: dict) -> Node:
    """core/v1 Node -> the framework Node the agent registry also uses:
    allocatable google.com/tpu chips become spec.chips, the ICI-domain
    label rides metadata.labels, cordon maps onto spec.unschedulable.
    Taints and allocatable cpu/mem feed the binder's hard placement
    filters; the node agent's heartbeat annotation feeds the operator's
    barrier-capability check (docs/node-agent.md)."""
    from tf_operator_tpu.controller.binder import (
        parse_cpu_quantity_millis,
        parse_memory_quantity_bytes,
    )

    meta = _meta_from_k8s(d.get("metadata") or {})
    meta.namespace = ""  # cluster-scoped
    spec_d = d.get("spec") or {}
    status_d = d.get("status") or {}
    address = ""
    for addr in status_d.get("addresses") or []:
        if addr.get("type") == "InternalIP":
            address = addr.get("address", "")
            break
    allocatable = status_d.get("allocatable") or {}
    try:
        chips = int(float(allocatable.get(constants.RESOURCE_TPU, 0) or 0))
    except ValueError:
        chips = 0
    conditions: Dict[str, str] = {}
    for cond in status_d.get("conditions") or []:
        ctype = cond.get("type", "")
        if ctype:
            conditions[ctype] = cond.get("status", "")
    # A node with NO Ready condition at all (kubelet never heartbeated)
    # is NotReady — kube-scheduler's conservative convention. Defaulting
    # to Ready would put its chips into the gang admission budget and
    # let the binder target a node nothing is serving on.
    ready = "Ready" if conditions.get("Ready") == "True" else "NotReady"
    taints = [Taint(key=t.get("key", ""), value=t.get("value", ""),
                    effect=t.get("effect", ""))
              for t in spec_d.get("taints") or []]
    return Node(metadata=meta,
                spec=NodeSpec(address=address, chips=chips,
                              labels=dict(meta.labels),
                              unschedulable=bool(
                                  spec_d.get("unschedulable")),
                              taints=taints),
                status=NodeStatus(
                    phase=ready, conditions=conditions,
                    last_heartbeat=parse_time(meta.annotations.get(
                        constants.ANNOTATION_AGENT_HEARTBEAT)),
                    allocatable_cpu_millis=parse_cpu_quantity_millis(
                        allocatable.get("cpu")),
                    allocatable_memory_bytes=parse_memory_quantity_bytes(
                        allocatable.get("memory"))))


FROM_K8S: Dict[str, Callable[[dict], object]] = {
    store_mod.TPUJOBS: tpujob_from_k8s,
    store_mod.PODS: pod_from_k8s,
    store_mod.ENDPOINTS: endpoint_from_k8s_service,
    store_mod.NODES: node_from_k8s,
}


# ---------------------------------------------------------------------------
# Controls (reference RealPodControl / RealServiceControl)
# ---------------------------------------------------------------------------

from tf_operator_tpu.controller.control import (  # noqa: E402
    EndpointControl,
    PodControl,
    controller_owner_ref,
)


class KubePodControl(PodControl):
    def __init__(self, client: KubeClient, recorder: Recorder):
        self.client = client
        self.recorder = recorder

    def create_pod(self, namespace: str, pod: Pod, job: TPUJob) -> None:
        pod.metadata.namespace = namespace
        pod.metadata.owner_references = [controller_owner_ref(job)]
        try:
            self.client.create(store_mod.PODS, namespace, pod_to_k8s(pod))
        except Exception as e:
            self.recorder.event(job, EVENT_TYPE_WARNING, "FailedCreatePod",
                                f"Error creating: {e}")
            raise
        self.recorder.event(job, EVENT_TYPE_NORMAL, "SuccessfulCreatePod",
                            f"Created pod: {pod.metadata.name}")
        metrics.created_pods.inc(job_namespace=namespace)

    def delete_pod(self, namespace: str, name: str, job: TPUJob) -> None:
        try:
            self.client.delete(store_mod.PODS, namespace, name)
        except store_mod.NotFoundError:
            return
        except Exception as e:
            self.recorder.event(job, EVENT_TYPE_WARNING, "FailedDeletePod",
                                f"Error deleting: {e}")
            raise
        self.recorder.event(job, EVENT_TYPE_NORMAL, "SuccessfulDeletePod",
                            f"Deleted pod: {name}")
        metrics.deleted_pods.inc(job_namespace=namespace)


class KubeEndpointControl(EndpointControl):
    def __init__(self, client: KubeClient, recorder: Recorder):
        self.client = client
        self.recorder = recorder

    def create_endpoint(self, namespace: str, endpoint: Endpoint,
                        job: TPUJob) -> None:
        endpoint.metadata.namespace = namespace
        endpoint.metadata.owner_references = [controller_owner_ref(job)]
        self.client.create(store_mod.ENDPOINTS, namespace,
                           service_to_k8s(endpoint))
        metrics.created_endpoints.inc(job_namespace=namespace)

    def delete_endpoint(self, namespace: str, name: str, job: TPUJob) -> None:
        try:
            self.client.delete(store_mod.ENDPOINTS, namespace, name)
        except store_mod.NotFoundError:
            return
        metrics.deleted_endpoints.inc(job_namespace=namespace)


class KubePdbControl:
    """PodDisruptionBudget sync for gang-scheduled jobs (reference
    SyncPdb, common/job_controller.go:247-284): one PDB per job, named
    after it, minAvailable = the gang's minMember, selecting the job's
    pods — so the CLUSTER's eviction machinery (node drains, autoscaler)
    can't shrink a gang below its all-or-nothing threshold out from
    under the scheduler. Owner-referenced: cluster GC reaps it with the
    job; delete() covers backends without GC (the fake)."""

    def __init__(self, client: KubeClient, recorder: Recorder):
        self.client = client
        self.recorder = recorder

    def sync(self, job: TPUJob, min_available: int) -> None:
        """Level-triggered like the reference (SyncPdb GETs every
        reconcile): recreate an out-of-band-deleted PDB, patch
        minAvailable when the gang's threshold changes."""
        ns, name = job.metadata.namespace, job.metadata.name
        want = int(min_available)
        try:
            current = None
            try:
                current = self.client.get(KIND_PDBS, ns, name)
            except store_mod.NotFoundError:
                pass
            if current is None:
                self.client.create(KIND_PDBS, ns, {
                    "apiVersion": "policy/v1",
                    "kind": "PodDisruptionBudget",
                    "metadata": {
                        "name": name,
                        "ownerReferences": [
                            controller_owner_ref(job).to_dict()],
                    },
                    "spec": {
                        "minAvailable": want,
                        "selector": {"matchLabels": {
                            constants.LABEL_JOB_NAME: name}},
                    },
                })
                self.recorder.event(job, EVENT_TYPE_NORMAL,
                                    "SuccessfulCreatePdb",
                                    f"Created PDB: {name} "
                                    f"(minAvailable={want})")
            elif (current.get("spec") or {}).get("minAvailable") != want:
                self.client.patch(KIND_PDBS, ns, name,
                                  {"spec": {"minAvailable": want}})
                self.recorder.event(job, EVENT_TYPE_NORMAL,
                                    "SuccessfulUpdatePdb",
                                    f"PDB {name} minAvailable -> {want}")
        except store_mod.AlreadyExistsError:
            pass  # concurrent leader won the create; next sync verifies
        except Exception as e:
            # Best-effort (the reference tolerates pdb failure the same
            # way): gang admission itself doesn't depend on the PDB —
            # but degraded drain protection must be visible on the job.
            self.recorder.event(job, EVENT_TYPE_WARNING, "FailedSyncPdb",
                                f"Error syncing PDB: {e}")
            log.warning("pdb sync for %s/%s failed: %s", ns, name, e)

    def delete(self, job: TPUJob) -> None:
        try:
            self.client.delete(KIND_PDBS, job.metadata.namespace,
                               job.metadata.name)
        except store_mod.NotFoundError:
            pass
        except Exception as e:
            self.recorder.event(job, EVENT_TYPE_WARNING, "FailedDeletePdb",
                                f"Error deleting PDB: {e}")
            log.warning("pdb delete for %s/%s failed: %s",
                        job.metadata.namespace, job.metadata.name, e)


# ---------------------------------------------------------------------------
# Informer: cluster state -> Store cache
# ---------------------------------------------------------------------------

# Reflector failure backoff (client-go reflector backoff analog).
_BACKOFF_BASE = 0.5
_BACKOFF_CAP = 30.0


class _Reflector:
    """Shared list+watch+reconnect loop (client-go reflector analog):
    relist, stream the watch, relist again on expiry/error, abortable
    mid-read. Subclasses supply ``_on_list(first, items)`` and
    ``_on_event(etype, raw)`` sinks."""

    def __init__(self, client: KubeClient, kind: str,
                 namespace: Optional[str] = None,
                 selector: Optional[Dict[str, str]] = None,
                 thread_name: str = ""):
        self.client = client
        self.kind = kind
        self.namespace = namespace
        self.selector = selector
        self._thread_name = thread_name or f"reflector-{kind}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._resp_box: list = []
        self._failures = 0

    def start(self):
        self._thread = threading.Thread(target=self._run,
                                        name=self._thread_name, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # Abort a blocking watch read so shutdown doesn't wait out the
        # stream timeout. close() alone is not enough: it only drops the
        # fd reference, and a recv() already blocked inside the reflector
        # thread keeps the socket alive until the server's next keepalive
        # tick — shutdown() wakes that read immediately.
        for resp in self._resp_box:
            sock = getattr(getattr(resp, "fp", None), "raw", None)
            sock = getattr(sock, "_sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                resp.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _backoff_seconds(self) -> float:
        """Exponential backoff with full jitter (client-go's reflector
        backoff manager semantics: grow to a cap, never hot-loop, add
        jitter so restarted reflectors don't thundering-herd the API
        server)."""
        base = min(_BACKOFF_CAP, _BACKOFF_BASE * (2 ** min(
            self._failures - 1, 10)))
        return base * (0.5 + random.random() / 2)

    def _run(self) -> None:
        # Reference behavior (client-go reflector.go:166-302): list once,
        # then watch; when a watch stream ends normally RESUME watching
        # from lastSyncResourceVersion instead of relisting (relists are
        # O(collection) on the server); relist only on 410 Gone (history
        # compacted past our RV) or after an error.
        first = True
        rv: Optional[str] = None  # None = must (re)list before watching
        while not self._stop.is_set():
            try:
                if rv is None:
                    listing = self.client.list(self.kind, self.namespace,
                                               self.selector)
                    self._on_list(first, listing.get("items") or [])
                    first = False
                    rv = str((listing.get("metadata") or {})
                             .get("resourceVersion", "") or "0")
                for etype, raw in self.client.watch(
                        self.kind, self.namespace, self.selector, rv,
                        resp_box=self._resp_box):
                    if self._stop.is_set():
                        return
                    if etype == "BOOKMARK":
                        # Bookmark's only job: advance the resume point.
                        brv = str(((raw or {}).get("metadata") or {})
                                  .get("resourceVersion", "") or "")
                        if brv:
                            rv = brv
                        continue
                    if etype == "ERROR":
                        code = int((raw or {}).get("code", 410) or 410)
                        if code == 410:
                            # History compacted past our RV: relist —
                            # not a failure, no backoff.
                            rv = None
                            break
                        # Any other server-side watch error takes the
                        # failure path (backoff + escalating log) —
                        # otherwise a persistent error becomes a silent
                        # hot list/watch loop.
                        raise KubeApiError(code, (raw or {}).get(
                            "reason", "WatchError"),
                            (raw or {}).get("message", "watch error"))
                    self._on_event(etype, raw)
                    # Reset ONLY on a delivered event — a successful
                    # relist must not clear the counter, or a
                    # list-ok/watch-fails loop oscillates at failures<=1
                    # forever: backoff never grows and the escalated
                    # warning at 3 consecutive failures never fires.
                    self._failures = 0
                    erv = str(((raw or {}).get("metadata") or {})
                              .get("resourceVersion", "") or "")
                    if erv:
                        rv = erv
                # Normal stream end (server timeoutSeconds): fall through
                # with rv intact — the next iteration re-watches from the
                # last delivered event, losing nothing and listing
                # nothing.
            except Exception:
                if self._stop.is_set():
                    return
                self._failures += 1
                # A transient blip logs at debug; a PERSISTENT failure
                # (403 from missing RBAC, bad server, expired token)
                # must not hide there — it would look like a silent hang.
                logfn = (log.warning if self._failures == 3
                         or self._failures % 300 == 0 else log.debug)
                logfn("reflector %s retrying after %d consecutive "
                      "errors", self.kind, self._failures, exc_info=True)
                self._stop.wait(self._backoff_seconds())
                # After an error we cannot know what was missed: relist.
                rv = None

    def _on_list(self, first: bool, items) -> None:
        raise NotImplementedError

    def _on_event(self, etype: str, raw: dict) -> None:
        raise NotImplementedError


class KubeInformer(_Reflector):
    """List+watch one kind into the Store (reflector analog). The Store's
    watch fan-out then drives the controller handlers exactly as the
    local runtime does."""

    def __init__(self, client: KubeClient, store: Store, kind: str,
                 namespace: Optional[str] = None,
                 selector: Optional[Dict[str, str]] = None):
        super().__init__(client, kind, namespace, selector,
                         thread_name=f"informer-{kind}")
        self.store = store
        self._from_k8s = FROM_K8S[kind]
        self.synced = threading.Event()

    def _on_list(self, first: bool, items) -> None:
        seen = set()
        for raw in items:
            obj = self._from_k8s(raw)
            seen.add((obj.metadata.namespace, obj.metadata.name))
            self._upsert(obj)
        # Objects gone from the cluster but still cached: delete.
        for ns, name, _ in self.store.keys(self.kind):
            if (ns, name) not in seen:
                self.store.try_delete(self.kind, ns, name)
        self.synced.set()

    def _on_event(self, etype: str, raw: dict) -> None:
        obj = self._from_k8s(raw)
        if etype == store_mod.DELETED:
            self.store.try_delete(self.kind, obj.metadata.namespace,
                                  obj.metadata.name)
        else:
            self._upsert(obj)

    def _upsert(self, obj) -> None:
        cur = self.store.try_get(self.kind, obj.metadata.namespace,
                                 obj.metadata.name)
        if cur is None:
            try:
                self.store.create(self.kind, obj)
            except store_mod.AlreadyExistsError:
                self._upsert(obj)
            return
        # Skip no-op mirrors: a relist re-delivers every object, and an
        # unconditional update would fire MODIFIED -> enqueue for all.
        a, b = cur.to_dict(), obj.to_dict()
        a.get("metadata", {}).pop("resourceVersion", None)
        b.get("metadata", {}).pop("resourceVersion", None)
        if a == b:
            return
        obj.metadata.resource_version = cur.metadata.resource_version
        try:
            self.store.update(self.kind, obj)
        except (store_mod.ConflictError, store_mod.NotFoundError):
            pass  # racing mirror; the next event/relist converges


# ---------------------------------------------------------------------------
# Controller + operator assembly
# ---------------------------------------------------------------------------

from tf_operator_tpu.controller.engine import EngineConfig  # noqa: E402
from tf_operator_tpu.controller.gang import SliceGangScheduler  # noqa: E402
from tf_operator_tpu.controller.tpu_controller import (  # noqa: E402
    TPUJobController,
)


class KubeJobController(TPUJobController):
    """TPUJobController with the write path against the K8s API server;
    the Store remains the read cache fed by KubeInformer."""

    # Per-key serialization in the workqueue makes parallel sync workers
    # safe; 4 is the production default (a 1k-job fleet converges ~4x
    # faster through API-server write latency).
    DEFAULT_THREADINESS = 4

    def run(self, threadiness: int = DEFAULT_THREADINESS) -> None:
        super().run(threadiness=threadiness)

    def __init__(self, client: KubeClient, store: Optional[Store] = None,
                 **kwargs):
        super().__init__(store or Store(), **kwargs)
        self.client = client
        self.engine.pod_control = KubePodControl(client, self.recorder)
        self.engine.endpoint_control = KubeEndpointControl(client,
                                                           self.recorder)
        if self.engine.gang is not None:
            if getattr(self.engine.gang, "_pod_control_auto_bound", False):
                # Re-bind only the base class's auto-bound store control
                # — evictions must go through the API server here. An
                # explicitly constructed pod_control is respected.
                self.engine.gang.pod_control = self.engine.pod_control
            # Reference SyncPdb: protect admitted gangs from cluster
            # eviction machinery (drains/autoscaler) via a PDB.
            self.engine.gang.pdb_control = KubePdbControl(client,
                                                          self.recorder)

    def update_job_status_in_api(self, job: TPUJob) -> None:
        """Status-subresource merge patch (reference
        UpdateJobStatusInApiServer, tensorflow/status.go:222-240).

        Every status field the schema knows is present in the patch —
        unset ones as explicit JSON nulls — because a merge patch can
        only CLEAR a field it names (RFC 7386): omitting a field leaves
        the server's old value in place forever."""
        from tf_operator_tpu.runtime import retry as retry_mod

        body = job.status.to_dict(explicit_nulls=True)
        try:
            # Transient 5xx blips retry in place (runtime/retry.py) and
            # report into degraded-mode tracking; a chaos-injected 409
            # is retried too — the patch carries no resourceVersion
            # precondition, so replaying the same merge is the correct
            # RetryOnConflict body.
            retry_mod.with_retries(
                lambda: self.client.patch(
                    store_mod.TPUJOBS, job.metadata.namespace,
                    job.metadata.name, {"status": body},
                    subresource="status"),
                component="kube.status",
                retryable=lambda e: (retry_mod.is_transient(e)
                                     or isinstance(
                                         e, store_mod.ConflictError)),
                health=self.cp_health)
        except store_mod.NotFoundError:
            pass  # job deleted mid-sync
        except store_mod.ConflictError:
            pass  # injected CAS loss; the next sync rewrites

    def delete_job(self, job: TPUJob) -> None:
        try:
            self.client.delete(store_mod.TPUJOBS, job.metadata.namespace,
                               job.metadata.name)
        except store_mod.NotFoundError:
            pass
        self.expectations.delete_for_job(job.key())
        self.recorder.event(job, EVENT_TYPE_NORMAL, "SuccessfulDeleteJob",
                            f"Deleted job: {job.metadata.name}")

    def _persist_adoption(self, kind: str, obj):
        """Ownership patch against the API server (reference AdoptPod's
        strategic-merge patch, controller_ref_manager.go:208-221)."""
        ns, name = obj.metadata.namespace, obj.metadata.name
        # Uncached quorum recheck (reference GetPodsForJob re-reads the
        # job live before claiming, common/pod.go:241-252): the cache may
        # lag — the object could have been deleted and recreated (new
        # uid) or adopted by someone else since the informer mirrored it.
        try:
            raw = self.client.get(kind, ns, name)
        except store_mod.NotFoundError:
            return None
        live = FROM_K8S[kind](raw)
        if (live.metadata.uid != obj.metadata.uid
                or live.metadata.controller_ref() is not None):
            return None
        patch = {"metadata": {
            # Live resourceVersion precondition closes the GET->PATCH
            # window (the reference adopt patch carries a uid
            # precondition for the same race).
            "resourceVersion": k8s_resource_version(raw),
            "ownerReferences": [
                r.to_dict() for r in obj.metadata.owner_references]}}
        try:
            raw = self.client.patch(kind, ns, name, patch)
        except (store_mod.ConflictError, store_mod.NotFoundError):
            return None
        return FROM_K8S[kind](raw)

    def _persist_release(self, kind: str, obj, job: TPUJob) -> None:
        """ReleasePod analog against the API server: live-read, verify
        the same object still exists, then patch our ownerReference away
        under a resourceVersion precondition."""
        ns, name = obj.metadata.namespace, obj.metadata.name
        try:
            raw = self.client.get(kind, ns, name)
        except store_mod.NotFoundError:
            return  # deleted is released
        live = FROM_K8S[kind](raw)
        if live.metadata.uid != obj.metadata.uid:
            return  # recreated under the same name; not ours to touch
        refs = [r.to_dict() for r in live.metadata.owner_references
                if r.uid != job.metadata.uid]
        patch = {"metadata": {"resourceVersion": k8s_resource_version(raw),
                              "ownerReferences": refs}}
        try:
            self.client.patch(kind, ns, name, patch)
        except (store_mod.ConflictError, store_mod.NotFoundError):
            pass  # changed underneath us; the next sync reconverges

    def _garbage_collect(self, job: TPUJob) -> None:
        """The cluster's ownerReference GC collects pods/services; delete
        explicitly too so tests (and clusters with GC lag) converge, and
        reap the store-local SliceGroup. O(owned) via the cache's
        owner-UID index — this used to deepcopy every cached object in
        the namespace, three kinds over, per deleted job."""
        for kind in (store_mod.PODS, store_mod.ENDPOINTS):
            for ns, name in self.store.owned_keys(kind, job.metadata.uid):
                try:
                    self.client.delete(kind, ns, name)
                except store_mod.NotFoundError:
                    pass
        for ns, name in self.store.owned_keys(store_mod.SLICEGROUPS,
                                              job.metadata.uid):
            self.store.try_delete(store_mod.SLICEGROUPS, ns, name)


class KubeOperator:
    """Operator assembly against a Kubernetes cluster (the reference
    deployment shape: manifests/base/deployment.yaml runs exactly this)."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None,
                 enable_gang_scheduling: bool = False,
                 total_chips: Optional[int] = None,
                 gang_fairness: str = "aged",
                 gang_aging_seconds: float = 300.0,
                 gang_priority_classes: Optional[dict] = None,
                 gang_queue_quotas: Optional[dict] = None,
                 gang_preemption: bool = False,
                 gang_binder: bool = True,
                 slice_health: bool = True,
                 health_drain_grace_seconds: float = 0.0,
                 config: Optional[EngineConfig] = None,
                 post_events: bool = True,
                 degraded_after_seconds: float = 10.0,
                 enable_tenant_queues: bool = False,
                 queue_config: Optional[str] = None,
                 enable_ckpt_coordination: bool = False,
                 enable_serving: bool = False,
                 relay_dir: str = "",
                 agent_heartbeat_staleness_seconds: float = 30.0):
        from tf_operator_tpu.runtime.retry import ControlPlaneHealth

        self.client = client
        self.store = Store()
        self.post_events = post_events
        recorder = Recorder(sink=self._post_event if post_events else None)
        config = config or EngineConfig()
        # Degraded-mode tracker (runtime/retry.py, docs/robustness.md):
        # API writes report into it; while degraded the controller keeps
        # reconciling but defers new drains/reclaims/preemptions.
        self.cp_health = ControlPlaneHealth(
            threshold_seconds=degraded_after_seconds)
        if enable_tenant_queues and not enable_gang_scheduling:
            raise ValueError("tenant queues sit above gang admission: "
                             "--enable-tenant-queues requires "
                             "--enable-gang-scheduling")
        self.agent_heartbeat_staleness_seconds = \
            agent_heartbeat_staleness_seconds
        self.quota = None
        self.ckpt = None
        self.serving = None
        # (ns, pod) -> last ckpt-state annotation payload mirrored into
        # a CheckpointRecord (relist dedup for _on_pod_relay_event).
        self._ckpt_state_seen: Dict[Tuple[str, str], str] = {}
        if enable_ckpt_coordination:
            from tf_operator_tpu.controller.ckpt import (
                CheckpointCoordinator,
            )

            # Notice stamps go through the API server (the store is an
            # informer mirror — a direct write would be clobbered by
            # the next relist), and barrier opening is gated on fresh
            # node-agent heartbeats: a node without a live relay can't
            # deliver the notice, so the drain degrades to plain
            # eviction instead of waiting out a doomed barrier.
            self.ckpt = CheckpointCoordinator(
                self.store, recorder=recorder, namespace=namespace,
                annotate_pod=self._annotate_pod,
                barrier_capable=self._barrier_capable)
        if enable_serving:
            from tf_operator_tpu.controller.serving import ServingManager

            self.serving = ServingManager(self.store, recorder=recorder,
                                          namespace=namespace)
        gang = None
        if enable_gang_scheduling:
            config.enable_gang_scheduling = True
            if enable_tenant_queues:
                from tf_operator_tpu.controller.quota import (
                    TenantQueueManager,
                    load_queue_config,
                    seed_queues,
                )

                # Queues/ClusterQueues are operator-internal kinds (no
                # CRD): on kube they live in the in-memory store and are
                # seeded from --queue-config (docs/quota.md Scope).
                self.quota = TenantQueueManager(self.store,
                                                recorder=recorder)
                if queue_config:
                    seed_queues(self.store,
                                *load_queue_config(queue_config))
            gang = SliceGangScheduler(self.store, total_chips=total_chips,
                                      fairness=gang_fairness,
                                      aging_seconds=gang_aging_seconds,
                                      priority_classes=gang_priority_classes,
                                      queue_quotas=gang_queue_quotas,
                                      preemption=gang_preemption,
                                      quota=self.quota,
                                      ckpt=self.ckpt,
                                      recorder=recorder,
                                      # Node-bound Pending pods (container
                                      # creating) already hold chips here;
                                      # nothing stamps gang_released on
                                      # the kube data plane.
                                      scheduled_pods_occupy=True,
                                      # With the in-operator binder, an
                                      # unset chip budget follows live
                                      # node inventory instead of being
                                      # unlimited.
                                      capacity_provider=(
                                          self._cluster_chip_capacity
                                          if gang_binder
                                          and total_chips is None
                                          else None),
                                      # Structural per-slice ceiling: a
                                      # slice no ICI domain can hold is
                                      # infeasible, not admitted-and-
                                      # stuck (binder can't split it).
                                      # Only when capacity is node-
                                      # derived — an explicit
                                      # --total-chips overrides node
                                      # accounting wholesale.
                                      domain_capacity_provider=(
                                          self._max_domain_chip_capacity
                                          if gang_binder
                                          and total_chips is None
                                          else None),
                                      cp_health=self.cp_health)
        self.controller = KubeJobController(client, store=self.store,
                                            recorder=recorder, config=config,
                                            gang=gang, namespace=namespace,
                                            cp_health=self.cp_health,
                                            ckpt=self.ckpt,
                                            serving=self.serving,
                                            relay_dir=relay_dir)
        if self.ckpt is not None and gang is not None:
            # A barrier ack landing between resyncs must release the
            # held eviction promptly: record writes poke admission.
            self.ckpt.on_ack = gang.readmit
        # Pods/services are watched UNSELECTED (upstream controller
        # style): a selector watch would drop an owned pod from the cache
        # the moment its group label is edited away, making it invisible
        # to the release path and leaving a stale ownerReference to
        # cascade-delete it later.
        self.informers = [
            KubeInformer(client, self.store, store_mod.TPUJOBS, namespace),
            KubeInformer(client, self.store, store_mod.PODS, namespace),
            KubeInformer(client, self.store, store_mod.ENDPOINTS, namespace),
        ]
        self.binder = None
        self.health = None
        # Nodes are cluster-scoped: informer namespace is always None.
        # The binder needs them for placement; the checkpoint
        # coordinator needs them for agent-heartbeat freshness even
        # without the binder.
        if (enable_gang_scheduling and gang_binder) \
                or enable_ckpt_coordination:
            self.informers.append(
                KubeInformer(client, self.store, store_mod.NODES, None))
        self._relay_watcher = None
        if enable_ckpt_coordination:
            # The node agent mirrors each worker's checkpoint file onto
            # the pod's ckpt-state annotation; the PODS informer carries
            # it here, where it becomes the pod's (in-memory)
            # CheckpointRecord — the same object the local data plane
            # publishes directly (runtime/relay.py).
            self._relay_watcher = self.store.watch(
                store_mod.PODS, self._on_pod_relay_event)
        if enable_gang_scheduling and gang_binder:
            from tf_operator_tpu.controller.binder import SliceGangBinder

            self.binder = SliceGangBinder(self.store, client, gang,
                                          namespace=namespace,
                                          recorder=recorder)
            if slice_health:
                # Slice-health & auto-repair rides the same node
                # inventory the binder placed from: maintenance-aware
                # cordon + gang drain/rebind (controller/health.py).
                from tf_operator_tpu.controller.health import (
                    SliceHealthController,
                )

                self.health = SliceHealthController(
                    self.store, client=client, gang=gang,
                    pod_control=self.controller.engine.pod_control,
                    recorder=recorder, namespace=namespace,
                    default_grace_seconds=health_drain_grace_seconds,
                    ckpt=self.ckpt, cp_health=self.cp_health)

    # -- node-agent relay plumbing (docs/node-agent.md) ------------------

    def _annotate_pod(self, namespace: str, name: str,
                      annotations: Dict[str, str]) -> None:
        """Checkpoint-coordinator stamp hook: annotations go through the
        API server (merge PATCH); the informer mirrors them back and the
        node agent's own watch relays them to the worker."""
        self.client.patch(store_mod.PODS, namespace, name,
                          {"metadata": {"annotations": dict(annotations)}})

    def _barrier_capable(self, pods) -> bool:
        """A gang is barrier-capable only when EVERY node hosting one of
        its live pods has a fresh node-agent heartbeat — otherwise the
        preemption notice would never reach some worker as a file and
        the barrier could only time out. Unbound pods have no relay
        either. Degrading (returning False) reproduces today's
        no-coordination eviction exactly (docs/node-agent.md)."""
        import datetime as _dt

        node_names = {p.spec.node_name for p in pods if p.spec.node_name}
        if not node_names:
            return False
        now = _dt.datetime.now(_dt.timezone.utc)
        for node_name in node_names:
            node = self.store.try_get(store_mod.NODES, "", node_name)
            if node is None or node.status.last_heartbeat is None:
                return False
            hb = node.status.last_heartbeat
            if hb.tzinfo is None:
                hb = hb.replace(tzinfo=_dt.timezone.utc)
            if (now - hb).total_seconds() \
                    > self.agent_heartbeat_staleness_seconds:
                return False
        return True

    def _on_pod_relay_event(self, etype: str, pod: Pod) -> None:
        """Convert the agent-mirrored ckpt-state annotation into the
        pod's CheckpointRecord (operator-internal kind — lives only in
        this in-memory store, so the informer can't clobber it)."""
        key = (pod.metadata.namespace, pod.metadata.name)
        if etype == store_mod.DELETED:
            self._ckpt_state_seen.pop(key, None)
            return
        raw = pod.metadata.annotations.get(
            constants.ANNOTATION_CKPT_STATE, "")
        if not raw or self._ckpt_state_seen.get(key) == raw:
            return
        try:
            data = json.loads(raw)
        except ValueError:
            return
        if not isinstance(data, dict):
            return
        import datetime as _dt

        from tf_operator_tpu.runtime import relay as relay_mod

        try:
            if relay_mod.upsert_checkpoint_record(
                    self.store, pod, data,
                    _dt.datetime.now(_dt.timezone.utc)):
                self._ckpt_state_seen[key] = raw
        except Exception:
            log.debug("ckpt-state mirror for %s/%s failed", *key,
                      exc_info=True)

    def _cluster_chip_capacity(self) -> int:
        """Gang admission budget from live node inventory: allocatable
        TPU chips across schedulable, Ready nodes (Volcano allocator
        analog — a cordoned or dead-kubelet node's chips must not admit
        a gang the binder then cannot place).

        Single-tenant assumption (documented at the --gang-binder flag
        and docs/health.md): chips held by pods outside the operator's
        bookkeeping — foreign controllers, or other namespaces when the
        operator is namespaced — are invisible to admission occupancy,
        so on a shared cluster a group can be admitted yet sit
        unplaceable at the binder until the foreign pods leave."""
        from tf_operator_tpu.controller.binder import node_is_schedulable

        total = 0
        for n in self.store.list(store_mod.NODES):
            if node_is_schedulable(n):
                total += n.spec.chips
        return total

    def _max_domain_chip_capacity(self) -> Optional[int]:
        """Largest single ICI domain's chip capacity — the structural
        ceiling for ONE slice. A slice bigger than every domain can
        never be placed whole; admission must not book budget for it
        (gang.py domain_capacity_provider). None when no nodes are
        known: zero topology knowledge must not flag everything
        infeasible (the capacity budget already gates admission)."""
        from tf_operator_tpu.controller.binder import (
            node_ici_domain,
            node_is_schedulable,
        )

        per_domain: Dict[str, int] = {}
        for n in self.store.list(store_mod.NODES):
            if node_is_schedulable(n):
                dom = node_ici_domain(n)
                per_domain[dom] = per_domain.get(dom, 0) + n.spec.chips
        return max(per_domain.values(), default=None)

    def start(self, threadiness: int = KubeJobController.DEFAULT_THREADINESS,
              sync_timeout: float = 30.0) -> None:
        for inf in self.informers:
            inf.start()
        # WaitForCacheSync analog (reference controller.go:201).
        for inf in self.informers:
            if not inf.synced.wait(timeout=sync_timeout):
                raise TimeoutError(f"informer {inf.kind} never synced "
                                   f"(API server unreachable?)")
        if self.ckpt is not None:
            self.ckpt.start()
        self.controller.run(threadiness=threadiness)
        if self.binder is not None:
            self.binder.start()
        if self.health is not None:
            self.health.start()
        log.info("kube operator started (threadiness=%d)", threadiness)

    def stop(self) -> None:
        if self.health is not None:
            self.health.stop()
        if self.binder is not None:
            self.binder.stop()
        self.controller.stop()
        if self.ckpt is not None:
            self.ckpt.stop()
        if self._relay_watcher is not None:
            self._relay_watcher.stop()
            self._relay_watcher = None
        for inf in self.informers:
            inf.stop()
        self.store.stop_watchers()

    def _post_event(self, ev) -> None:
        """Mirror recorder events as core/v1 Events (reference recorder
        wiring, common/job_controller.go:158-162)."""
        import uuid

        ns = ev.namespace or "default"
        body = {
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"name": f"{ev.object_name}.{uuid.uuid4().hex[:10]}",
                         "namespace": ns},
            "involvedObject": {"kind": ev.object_kind,
                               "name": ev.object_name, "namespace": ns},
            "type": ev.type, "reason": ev.reason, "message": ev.message,
            "source": {"component": "tpu-operator"},
        }
        try:
            self.client.create_event(ns, body)
        except Exception:
            log.debug("event post failed", exc_info=True)


def check_crd_exists(client: KubeClient) -> bool:
    """Fail-fast CRD existence probe (reference checkCRDExists,
    app/server.go:232-251). Only a definitive 404 means "not installed";
    auth/server errors propagate so they aren't misdiagnosed as a
    missing CRD."""
    try:
        client.request(
            "GET",
            f"/apis/apiextensions.k8s.io/v1/customresourcedefinitions/"
            f"{constants.CRD_NAME}")
        return True
    except store_mod.NotFoundError:
        return False


# ---------------------------------------------------------------------------
# Leader election over coordination.k8s.io Leases
# ---------------------------------------------------------------------------

class KubeLeaseStore:
    """Duck-types the Store subset LeaderElector uses (try_get / create /
    update on the LEASES kind), backed by coordination.k8s.io/v1 Leases:
    the cluster-wide lock the reference took on an Endpoints object
    (app/server.go:168-193) and modern client-go takes on exactly this
    resource. Optimistic concurrency maps onto resourceVersion'd PUTs."""

    def __init__(self, client: KubeClient):
        self.client = client
        # (ns, name) -> raw K8s resourceVersion string for CAS replays.
        self._rv: Dict[Tuple[str, str], str] = {}

    @staticmethod
    def _path(ns: str, name: str = "") -> str:
        base = f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"
        return f"{base}/{name}" if name else base

    @staticmethod
    def _spec_to_k8s(lease) -> dict:
        import math

        spec = lease.spec.to_dict()
        # K8s LeaseSpec wants an integer duration; round UP so a
        # sub-second duration never truncates to an always-expired 0.
        if spec.get("leaseDurationSeconds") is not None:
            spec["leaseDurationSeconds"] = math.ceil(
                spec["leaseDurationSeconds"])
        return spec

    def _from_k8s(self, raw: dict):
        from tf_operator_tpu.runtime.leaderelection import Lease

        lease = Lease.from_dict({"spec": raw.get("spec") or {}})
        lease.metadata = _meta_from_k8s(raw.get("metadata") or {})
        key = (lease.metadata.namespace, lease.metadata.name)
        self._rv[key] = k8s_resource_version(raw)
        return lease

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            raw = self.client.request("GET", self._path(namespace, name))
        except store_mod.NotFoundError:
            return None
        return self._from_k8s(raw)

    def create(self, kind: str, lease):
        ns = lease.metadata.namespace
        body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": lease.metadata.name, "namespace": ns},
                "spec": self._spec_to_k8s(lease)}
        return self._from_k8s(
            self.client.request("POST", self._path(ns), body=body))

    def update(self, kind: str, lease):
        ns, name = lease.metadata.namespace, lease.metadata.name
        body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name, "namespace": ns,
                             "resourceVersion": self._rv.get((ns, name), "")},
                "spec": self._spec_to_k8s(lease)}
        return self._from_k8s(
            self.client.request("PUT", self._path(ns, name), body=body))


# ---------------------------------------------------------------------------
# SDK-facing store adapter: TPUJobClient directly against a K8s cluster
# ---------------------------------------------------------------------------

class _KubeWatcher(_Reflector):
    """Store.Watcher analog over a K8s watch stream: delivers translated
    (event_type, obj) pairs to a handler, surviving stream expiry."""

    def __init__(self, client: KubeClient, kind: str,
                 handler: Callable[[str, object], None],
                 namespace: Optional[str], replay: bool,
                 from_k8s: Callable[[dict], object],
                 on_stop: Optional[Callable[["_KubeWatcher"], None]] = None):
        super().__init__(client, kind, namespace,
                         thread_name=f"kube-watch-{kind}")
        self.handler = handler
        self.replay = replay
        self._from_k8s = from_k8s
        self._notify_stop = on_stop
        # (ns, name) -> last delivered object, for synthesizing DELETED
        # after a disconnect gap.
        self._known: Dict[Tuple[str, str], object] = {}
        self.start()

    def _on_list(self, first: bool, items) -> None:
        seen: Dict[Tuple[str, str], object] = {}
        for raw in items:
            obj = self._from_k8s(raw)
            seen[(obj.metadata.namespace, obj.metadata.name)] = obj
        # First relist replays as ADDED (informer initial list);
        # RECONNECT relists re-deliver as MODIFIED so state that changed
        # in the disconnect gap (e.g. a job finishing during a
        # 410/timeout window) is never lost, and objects that VANISHED
        # in the gap get a synthesized DELETED (a watch(until_finished)
        # consumer would otherwise block forever on a deleted job).
        if self.replay or not first:
            etype = store_mod.ADDED if first else store_mod.MODIFIED
            for obj in seen.values():
                self.handler(etype, obj)
        if not first:
            for key, obj in self._known.items():
                if key not in seen:
                    self.handler(store_mod.DELETED, obj)
        self._known = seen

    def _on_event(self, etype: str, raw: dict) -> None:
        obj = self._from_k8s(raw)
        key = (obj.metadata.namespace, obj.metadata.name)
        if etype == store_mod.DELETED:
            self._known.pop(key, None)
        else:
            self._known[key] = obj
        self.handler(etype, obj)

    def stop(self) -> None:
        super().stop()
        if self._notify_stop is not None:
            self._notify_stop(self)


def _event_from_k8s(d: dict) -> "object":
    from tf_operator_tpu.api.types import EventRecord

    involved = d.get("involvedObject") or {}
    record = EventRecord(
        metadata=_meta_from_k8s(d.get("metadata") or {}),
        involved_kind=involved.get("kind", ""),
        involved_name=involved.get("name", ""),
        type=d.get("type", ""),
        reason=d.get("reason", ""),
        message=d.get("message", ""))
    # The in-process recorder stamps a job-name label; K8s Events carry
    # the target in involvedObject instead — reconstruct the label so
    # label-selector consumers (get_events) work unchanged.
    if record.involved_kind == constants.KIND:
        record.metadata.labels.setdefault(constants.LABEL_JOB_NAME,
                                          record.involved_name)
    return record


class KubeSdkStore:
    """Duck-types the Store surface ``TPUJobClient`` uses, directly
    against a Kubernetes cluster — the reference SDK's deployment shape
    (kubernetes-client from kubeconfig, tf_job_client.py:55-100):
    TPUJob CRs, pods, Events, watches, and the pod-log API."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None):
        self.client = client
        # Watches scope to this namespace when set: a namespaced Role
        # (the common non-admin kubeconfig) cannot list cluster-wide,
        # and the SDK filters to one namespace anyway.
        self.namespace = namespace
        self._watchers: list = []

    @staticmethod
    def _to_k8s(kind: str, obj) -> dict:
        if kind == store_mod.TPUJOBS:
            return tpujob_to_k8s(obj)
        if kind == store_mod.PODS:
            return pod_to_k8s(obj)
        if kind == store_mod.ENDPOINTS:
            return service_to_k8s(obj)
        raise KeyError(f"unsupported kind {kind!r}")

    @staticmethod
    def _from_k8s(kind: str, raw: dict):
        if kind == store_mod.EVENTS:
            return _event_from_k8s(raw)
        return FROM_K8S[kind](raw)

    # -- CRUD -----------------------------------------------------------

    def create(self, kind: str, obj):
        ns = obj.metadata.namespace or "default"
        return self._from_k8s(kind, self.client.create(
            kind, ns, self._to_k8s(kind, obj)))

    def get(self, kind: str, namespace: str, name: str):
        return self._from_k8s(kind, self.client.get(kind, namespace, name))

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except store_mod.NotFoundError:
            return None

    def update(self, kind: str, obj):
        """Full replace under the object's resourceVersion (optimistic
        concurrency — the cluster returns 409 on a stale rv, surfaced
        as ConflictError for the SDK's read-modify-write retry)."""
        ns, name = obj.metadata.namespace, obj.metadata.name
        body = self._to_k8s(kind, obj)
        body.setdefault("metadata", {})["resourceVersion"] = \
            str(obj.metadata.resource_version or "")
        raw = self.client.request("PUT", self.client._path(kind, ns, name),
                                  body=body)
        return self._from_k8s(kind, raw)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.client.delete(kind, namespace, name)

    def try_delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self.client.delete(kind, namespace, name)
            return True
        except store_mod.NotFoundError:
            return False

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None):
        if kind == store_mod.EVENTS:
            # K8s Events carry no useful labels. A job-name selector maps
            # onto the server-side involvedObject fieldSelector (a busy
            # shared namespace holds thousands of foreign events);
            # remaining label constraints filter on the reconstructed
            # labels client-side.
            field_selector = ""
            if selector and selector.get(constants.LABEL_JOB_NAME):
                field_selector = ("involvedObject.name="
                                  f"{selector[constants.LABEL_JOB_NAME]}")
            items = [self._from_k8s(kind, raw) for raw in
                     self.client.list(kind, namespace,
                                      field_selector=field_selector)
                     .get("items") or []]
            if selector:
                items = [e for e in items if store_mod.matches_selector(
                    e.metadata.labels, selector)]
            return items
        return [self._from_k8s(kind, raw) for raw in
                self.client.list(kind, namespace,
                                 selector).get("items") or []]

    # -- watch ----------------------------------------------------------

    def watch(self, kind: str, handler, replay: bool = True):
        w = _KubeWatcher(self.client, kind, handler, self.namespace,
                         replay,
                         from_k8s=lambda raw: self._from_k8s(kind, raw),
                         on_stop=self._remove_watcher)
        self._watchers.append(w)
        return w

    def _remove_watcher(self, w) -> None:
        try:
            self._watchers.remove(w)
        except ValueError:
            pass  # already removed (stop_watchers or double stop)

    def stop_watchers(self) -> None:
        watchers, self._watchers = self._watchers, []
        for w in watchers:
            w.stop()

    # -- pod logs (kubelet log API) --------------------------------------

    def read_logs(self, namespace: str, pod_name: str,
                  tail_lines: Optional[int] = None) -> str:
        params = {}
        if tail_lines is not None:
            params["tailLines"] = str(tail_lines)
        try:
            resp = self.client.request(
                "GET",
                f"/api/v1/namespaces/{namespace}/pods/{pod_name}/log",
                params=params, stream=True)
        except store_mod.NotFoundError:
            return ""  # transport parity: a vanished pod has no logs
        with resp:
            text = resp.read().decode("utf-8", "replace")
        if tail_lines == 0:
            return ""
        return text

    def stream_logs(self, namespace: str, pod_name: str):
        try:
            resp = self.client.request(
                "GET",
                f"/api/v1/namespaces/{namespace}/pods/{pod_name}/log",
                params={"follow": "true"}, timeout=None, stream=True)
        except store_mod.NotFoundError:
            return  # transport parity: empty stream for a vanished pod
        try:
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                yield chunk.decode("utf-8", "replace")
        finally:
            resp.close()
