"""In-memory object store with watch — the API-server/etcd analog.

The reference talks to the Kubernetes API server through clientsets and
shared informers (pkg/client/, cmd/tf-operator.v1/app/server.go:129-144).
This store provides the same contract process-natively so the whole
control loop runs hermetically:

- CRUD with uid assignment, resourceVersion bumps and optimistic
  concurrency on update;
- label-selector list;
- watch: registered handlers receive (ADDED/MODIFIED/DELETED, object)
  callbacks on a dispatcher thread per watcher (informer analog). Every
  event is deepcopied ONCE and that snapshot is shared by all watchers
  — handlers must not mutate delivered objects (the informer-cache
  immutability discipline the reference relies on, controller.go:325).
  A per-kind watch log lets reconnecting watchers resume from a known
  resourceVersion (``watch(since_rv=...)``) instead of replaying the
  world as ADDED.

Scale discipline (the reconcile hot path syncs ~1k jobs x ~10k pods):

- Two indexes are maintained on every write — per
  ``(namespace, job-name label)`` and per controller-owner UID — so
  ``list_claimable`` and ``owned_keys`` touch only a job's own objects
  instead of scanning the namespace (client-go Indexer analog).
- Stored objects are never mutated in place: every write deepcopies the
  inbound object and REPLACES the slot, so a stored object is an
  immutable snapshot from the moment it lands. ``list_claimable``
  exploits that by returning the stored objects themselves (frozen;
  callers deepcopy before mutating) instead of deepcopying the whole
  claimed set on every sync.
"""

from __future__ import annotations

import collections
import copy
import datetime as _dt
import queue
import threading
import uuid
from typing import Callable, Dict, List, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# Per-kind watch-log capacity: a reconnecting watcher with a known
# resourceVersion replays deltas from this ring (watch-cache hit); a
# resume point older than the ring's tail falls back to the full ADDED
# replay. Sized for reconnect windows (seconds of events), not history.
WATCH_LOG_CAPACITY = 4096

# The label both indexes and the controller's base selector key on
# (api/constants.LABEL_JOB_NAME; duplicated literally — the store must
# stay importable without the api package).
INDEX_LABEL_JOB_NAME = "job-name"


class ConflictError(Exception):
    pass


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(Exception):
    pass


def matches_selector(labels: Dict[str, str],
                     selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class Watcher:
    def __init__(self, kind: str, handler: Callable[[str, object], None]):
        self.kind = kind
        self.handler = handler
        self.queue: "queue.Queue[Optional[Tuple[str, object]]]" = queue.Queue()
        self.error_count = 0
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                return
            event_type, obj = item
            try:
                self.handler(event_type, obj)
            except Exception:  # watch handlers must never kill the dispatcher
                # Swallowed-but-accounted: the traceback logs ONCE per
                # handler (a broken handler throws on every event — one
                # stack is diagnosis, thousands are log spam) and every
                # occurrence lands in the
                # store_watch_handler_errors_total{kind} metric so a
                # silently-failing reconcile trigger is visible on a
                # dashboard instead of only in drowned logs.
                import logging

                from tf_operator_tpu.runtime import metrics

                self.error_count += 1
                metrics.store_watch_handler_errors.inc(kind=self.kind)
                logger = logging.getLogger("tpu_operator.store")
                if self.error_count == 1:
                    logger.exception(
                        "watch handler error for %s (first occurrence; "
                        "further ones are counted in "
                        "store_watch_handler_errors_total and logged "
                        "without traceback)", self.kind)
                else:
                    logger.warning(
                        "watch handler error for %s (%d so far)",
                        self.kind, self.error_count)

    def stop(self) -> None:
        # Deregister from the store first so _notify stops enqueueing
        # into a dead queue (unbounded growth otherwise).
        on_stop = getattr(self, "_on_stop", None)
        if on_stop is not None:
            on_stop(self)
        self.queue.put(None)


class Store:
    def __init__(self):
        self._lock = threading.RLock()
        # kind -> {(namespace, name) -> obj}
        self._objects: Dict[str, Dict[Tuple[str, str], object]] = {}
        self._watchers: List[Watcher] = []
        # Last-assigned resourceVersion (plain int, not an iterator, so
        # latest_rv() can answer without consuming one).
        self._rv = 0
        # (kind, namespace, job-name label) -> {(ns, name), ...}
        self._label_index: Dict[Tuple[str, str, str], set] = {}
        # (kind, controller-owner uid) -> {(ns, name), ...}
        self._owner_index: Dict[Tuple[str, str], set] = {}
        # kind -> deque[(event rv, event type, frozen stored object)]:
        # the watch cache. Appended under the lock by every write;
        # watch(since_rv=...) replays deltas from it.
        self._watch_log: Dict[str, collections.deque] = {}
        # kind -> highest event rv ever evicted from the log (a resume
        # at or before this point has a gap -> full replay).
        self._watch_log_evicted: Dict[str, int] = {}
        # Plain-int mirrors of the watch-cache/pagination metrics, for
        # benches and tests that read the store without scraping the
        # registry (the registry is process-global and shared).
        self.watch_cache_hits = 0
        self.watch_cache_misses = 0
        self.list_pages = 0

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def latest_rv(self) -> int:
        """Highest resourceVersion assigned so far (0 = no writes yet):
        the resume point a watcher passes back as ``since_rv``."""
        with self._lock:
            return self._rv

    # -- indexes (maintained under the lock on every write) ---------------

    def _index_add(self, kind: str, key: Tuple[str, str], obj) -> None:
        job_name = obj.metadata.labels.get(INDEX_LABEL_JOB_NAME)
        if job_name:
            self._label_index.setdefault(
                (kind, key[0], job_name), set()).add(key)
        ref = obj.metadata.controller_ref()
        if ref is not None and ref.uid:
            self._owner_index.setdefault((kind, ref.uid), set()).add(key)

    def _index_remove(self, kind: str, key: Tuple[str, str], obj) -> None:
        job_name = obj.metadata.labels.get(INDEX_LABEL_JOB_NAME)
        if job_name:
            bucket = self._label_index.get((kind, key[0], job_name))
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._label_index[(kind, key[0], job_name)]
        ref = obj.metadata.controller_ref()
        if ref is not None and ref.uid:
            bucket = self._owner_index.get((kind, ref.uid))
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._owner_index[(kind, ref.uid)]

    # -- CRUD -------------------------------------------------------------

    def create(self, kind: str, obj) -> object:
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            if key in coll:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            # Identity is stamped on the CALLER's object and a deepcopy
            # becomes the stored snapshot — one copy per create (this
            # used to copy twice: once in, once back out). The return
            # value stays caller-owned and mutable; the store never
            # retains a reference to it.
            if not obj.metadata.uid:
                obj.metadata.uid = str(uuid.uuid4())
            if obj.metadata.creation_timestamp is None:
                obj.metadata.creation_timestamp = _dt.datetime.now(
                    _dt.timezone.utc)
            obj.metadata.resource_version = self._next_rv()
            stored = obj.deepcopy()
            coll[key] = stored
            self._index_add(kind, key, stored)
            self._notify(kind, ADDED, stored)
            return obj

    def get(self, kind: str, namespace: str, name: str) -> object:
        with self._lock:
            try:
                return self._objects[kind][(namespace, name)].deepcopy()
            except KeyError:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")

    def get_snapshot(self, kind: str, namespace: str, name: str):
        """The stored object itself — FROZEN — or None. The zero-copy
        point read: stored objects are never mutated in place (every
        write replaces the slot), so the snapshot stays valid forever;
        the caller must treat it as immutable and ``deepcopy()`` before
        mutating (the ``list_claimable`` contract, for a single key)."""
        with self._lock:
            return self._objects.get(kind, {}).get((namespace, name))

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[object]:
        with self._lock:
            out = []
            for (ns, _), obj in self._objects.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                if selector and not matches_selector(obj.metadata.labels,
                                                     selector):
                    continue
                out.append(obj.deepcopy())
            return out

    def project(self, kind: str, fn, namespace: Optional[str] = None):
        """Read-only projection under the lock WITHOUT deepcopying:
        collects ``fn(obj)`` for every object, skipping ``None``
        results. ``fn`` must treat the object as frozen — no mutation,
        no retaining references past the call (the cheap-scan pattern
        of list_claimable, generalized; a full list() deepcopies every
        payload, which hot per-sync scans must not)."""
        out = []
        with self._lock:
            for (ns, _), obj in self._objects.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                v = fn(obj)
                if v is not None:
                    out.append(v)
        return out

    def list_claimable(self, kind: str, namespace: str,
                       selector: Dict[str, str],
                       owner_uid: str) -> List[object]:
        """Objects a controller's claim pass must see: label matches OR
        already owned by ``owner_uid`` (covers owned objects whose
        labels stopped matching, which release needs).

        O(owned): candidates come from the job-name-label and owner-UID
        indexes, so a namespace full of other jobs' pods costs nothing
        (pre-index this scanned — and a full list() deepcopied — every
        object in the namespace per job sync). Falls back to the scan
        only for selectors without the indexed label.

        Returns FROZEN shared snapshots, not copies: stored objects are
        never mutated in place (every write replaces the slot), so the
        only contract is on the caller — treat the result as immutable
        and ``deepcopy()`` any object before mutating it (the claim
        pass does exactly that on its rare adopt/release edges)."""
        with self._lock:
            coll = self._objects.get(kind, {})
            job_name = (selector or {}).get(INDEX_LABEL_JOB_NAME)
            if job_name is None:
                candidates = [k for k in coll if k[0] == namespace]
            else:
                keys = set(self._label_index.get(
                    (kind, namespace, job_name), ()))
                keys.update(self._owner_index.get((kind, owner_uid), ()))
                candidates = sorted(keys)  # deterministic sync order
            out = []
            for key in candidates:
                obj = coll.get(key)
                if obj is None or key[0] != namespace:
                    continue
                if not matches_selector(obj.metadata.labels, selector):
                    ref = obj.metadata.controller_ref()
                    if ref is None or ref.uid != owner_uid:
                        continue
                out.append(obj)
            return out

    def owned_keys(self, kind: str, owner_uid: str) -> List[Tuple[str, str]]:
        """(namespace, name) keys of objects whose controller
        ownerReference is ``owner_uid`` — O(owned) via the owner index,
        no payload copies. The garbage-collection primitive: cascade
        deletes used to re-list (and deepcopy) whole namespaces."""
        with self._lock:
            return sorted(self._owner_index.get((kind, owner_uid), ()))

    def update(self, kind: str, obj) -> object:
        """Full-object update with optimistic concurrency: the caller's
        resourceVersion must match the stored one."""
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            current = coll.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            if (obj.metadata.resource_version
                    and obj.metadata.resource_version
                    != current.metadata.resource_version):
                raise ConflictError(
                    f"{kind} {key}: resourceVersion "
                    f"{obj.metadata.resource_version} != "
                    f"{current.metadata.resource_version}")
            # Same one-copy discipline as create: stamp the caller's
            # object, store a deepcopy, hand the caller's own object
            # back (its resourceVersion now current, so a follow-up
            # CAS write passes without a re-read).
            obj.metadata.uid = current.metadata.uid
            obj.metadata.creation_timestamp = current.metadata.creation_timestamp
            obj.metadata.resource_version = self._next_rv()
            stored = obj.deepcopy()
            self._index_remove(kind, key, current)
            coll[key] = stored
            self._index_add(kind, key, stored)
            self._notify(kind, MODIFIED, stored)
            return obj

    def update_status(self, kind: str, obj) -> object:
        """Status-subresource-style update: merges only .status (and
        completion metadata) into the stored object, avoiding spec clobber."""
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            current = coll.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            # Zero-copy merge: the new stored snapshot SHARES the
            # current one's frozen spec (neither is ever mutated in
            # place); only .status — the part that changed — is
            # deepcopied. This is the hottest write in the system (one
            # per kubelet phase transition and one per controller
            # sync), and it used to deepcopy the whole object twice
            # plus the status. The caller's resourceVersion is synced
            # in place so its working copy stays current; the return
            # is the FROZEN stored snapshot (callers treat it as
            # immutable, like every other snapshot read).
            stored = copy.copy(current)
            stored.metadata = copy.copy(current.metadata)
            stored.status = obj.status.deepcopy()
            stored.metadata.resource_version = self._next_rv()
            obj.metadata.resource_version = stored.metadata.resource_version
            # No index maintenance: a status merge cannot change the
            # labels/ownerRefs the (key-valued) indexes are built from.
            coll[key] = stored
            self._notify(kind, MODIFIED, stored)
            return stored

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            coll = self._objects.get(kind, {})
            obj = coll.pop((namespace, name), None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            self._index_remove(kind, (namespace, name), obj)
            # The DELETED event carries a fresh resourceVersion (on a
            # shallow tombstone — the popped snapshot stays frozen) so
            # resumed watchers order the delete after the object's last
            # modification and reconnecting clients can advance their
            # resume point past it.
            tomb = copy.copy(obj)
            tomb.metadata = copy.copy(obj.metadata)
            tomb.metadata.resource_version = self._next_rv()
            self._notify(kind, DELETED, tomb)

    def try_delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self.delete(kind, namespace, name)
            return True
        except NotFoundError:
            return False

    def count(self, kind: str) -> int:
        """Object count without the deepcopy cost of list()."""
        with self._lock:
            return len(self._objects.get(kind, {}))

    def keys(self, kind: str) -> List[Tuple[str, str, int]]:
        """(namespace, name, resourceVersion) tuples without deepcopying
        payloads — for pruning/housekeeping over large collections."""
        with self._lock:
            return [(ns, name, obj.metadata.resource_version)
                    for (ns, name), obj in self._objects.get(kind, {}).items()]

    def list_page(self, kind: str, namespace: Optional[str] = None,
                  selector: Optional[Dict[str, str]] = None,
                  limit: Optional[int] = None,
                  after: Optional[Tuple[str, str]] = None):
        """One page of a keyset-paginated list. Returns
        ``(items, next_after, rv)``: items sorted by (namespace, name)
        strictly after the ``after`` cursor, at most ``limit`` of them;
        feed ``next_after`` back as ``after`` to continue (None = walk
        complete); ``rv`` is the store's resourceVersion when the page
        was cut. The strictly-increasing key cursor makes a page walk
        exactly-once for every object that exists for its whole
        duration, regardless of concurrent writes between pages. Items
        are FROZEN stored snapshots — treat as immutable (serialize or
        deepcopy, never mutate)."""
        with self._lock:
            self.list_pages += 1
            from tf_operator_tpu.runtime import metrics

            metrics.list_pages.inc(kind=kind)
            coll = self._objects.get(kind, {})
            items: List[object] = []
            next_after = None
            for key in sorted(coll):
                if after is not None and key <= tuple(after):
                    continue
                obj = coll[key]
                if namespace is not None and key[0] != namespace:
                    continue
                if selector and not matches_selector(obj.metadata.labels,
                                                     selector):
                    continue
                items.append(obj)
                if limit is not None and limit > 0 and len(items) >= limit:
                    next_after = key
                    break
            return items, next_after, self._rv

    # -- watch ------------------------------------------------------------

    def watch(self, kind: str,
              handler: Callable[[str, object], None],
              replay: bool = True,
              since_rv: Optional[int] = None) -> Watcher:
        """Register a handler; with ``replay`` existing objects are
        delivered as ADDED first (informer initial list).

        ``since_rv`` is the reconnect path: "I have seen every event up
        to and including this resourceVersion". When the per-kind watch
        log still covers that point, only the missed deltas replay, in
        order (watch-cache hit — no ADDED storm); when the log has
        evicted past it, the watcher falls back to the full ADDED
        replay (miss — the reflector relist contract)."""
        with self._lock:
            w = Watcher(kind, handler)
            w._on_stop = self._remove_watcher
            replay_all = replay
            if since_rv is not None:
                if since_rv >= self._watch_log_evicted.get(kind, 0):
                    self.watch_cache_hits += 1
                    from tf_operator_tpu.runtime import metrics

                    metrics.watch_cache_hits.inc(kind=kind)
                    for entry_rv, et, obj in self._watch_log.get(kind, ()):
                        if entry_rv > since_rv:
                            w.queue.put((et, obj.deepcopy()))
                    replay_all = False
                else:
                    self.watch_cache_misses += 1
            if replay_all:
                for obj in self._objects.get(kind, {}).values():
                    w.queue.put((ADDED, obj.deepcopy()))
            self._watchers.append(w)
            return w

    def _remove_watcher(self, w: Watcher) -> None:
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass  # already removed (stop_watchers or double stop)

    def stop_watchers(self) -> None:
        with self._lock:
            watchers, self._watchers = self._watchers, []
        for w in watchers:
            w.stop()

    def _notify(self, kind: str, event_type: str, obj) -> None:
        # Callers hold self._lock. The frozen stored object lands in
        # the watch log (no copy — it is immutable); live watchers all
        # receive ONE shared deepcopy per event instead of one each
        # (handlers already must not mutate delivered objects; at fan-
        # out degree W this was W deepcopies per write).
        wlog = self._watch_log.setdefault(kind, collections.deque())
        wlog.append((obj.metadata.resource_version, event_type, obj))
        while len(wlog) > WATCH_LOG_CAPACITY:
            self._watch_log_evicted[kind] = wlog.popleft()[0]
        snap = None
        for w in self._watchers:
            if w.kind == kind:
                if snap is None:
                    snap = obj.deepcopy()
                w.queue.put((event_type, snap))


# Canonical collection names.
TPUJOBS = "tpujobs"
PODS = "pods"
ENDPOINTS = "endpoints"
SLICEGROUPS = "slicegroups"
EVENTS = "events"
NODES = "nodes"
# Multi-tenant admission (controller/quota.py). TENANTQUEUES is
# namespaced; CLUSTERQUEUES is cluster-scoped (stored under the
# reserved namespace "").
TENANTQUEUES = "tenantqueues"
CLUSTERQUEUES = "clusterqueues"
# Checkpoint coordination (controller/ckpt.py): one record per replica,
# named after the pod, labeled job-name — the save-before-evict barrier's
# ack channel and the restore-step source.
CHECKPOINTRECORDS = "checkpointrecords"
