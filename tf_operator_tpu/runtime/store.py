"""In-memory object store with watch — the API-server/etcd analog.

The reference talks to the Kubernetes API server through clientsets and
shared informers (pkg/client/, cmd/tf-operator.v1/app/server.go:129-144).
This store provides the same contract process-natively so the whole
control loop runs hermetically:

- CRUD with uid assignment, resourceVersion bumps and optimistic
  concurrency on update;
- label-selector list;
- watch: registered handlers receive (ADDED/MODIFIED/DELETED, object)
  callbacks on a dispatcher thread per watcher (informer analog — objects
  are deep-copied both ways, preserving the informer-cache immutability
  discipline the reference relies on, controller.go:325).

Scale discipline (the reconcile hot path syncs ~1k jobs x ~10k pods):

- Two indexes are maintained on every write — per
  ``(namespace, job-name label)`` and per controller-owner UID — so
  ``list_claimable`` and ``owned_keys`` touch only a job's own objects
  instead of scanning the namespace (client-go Indexer analog).
- Stored objects are never mutated in place: every write deepcopies the
  inbound object and REPLACES the slot, so a stored object is an
  immutable snapshot from the moment it lands. ``list_claimable``
  exploits that by returning the stored objects themselves (frozen;
  callers deepcopy before mutating) instead of deepcopying the whole
  claimed set on every sync.
"""

from __future__ import annotations

import datetime as _dt
import itertools
import queue
import threading
import uuid
from typing import Callable, Dict, List, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# The label both indexes and the controller's base selector key on
# (api/constants.LABEL_JOB_NAME; duplicated literally — the store must
# stay importable without the api package).
INDEX_LABEL_JOB_NAME = "job-name"


class ConflictError(Exception):
    pass


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(Exception):
    pass


def matches_selector(labels: Dict[str, str],
                     selector: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class Watcher:
    def __init__(self, kind: str, handler: Callable[[str, object], None]):
        self.kind = kind
        self.handler = handler
        self.queue: "queue.Queue[Optional[Tuple[str, object]]]" = queue.Queue()
        self.error_count = 0
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                return
            event_type, obj = item
            try:
                self.handler(event_type, obj)
            except Exception:  # watch handlers must never kill the dispatcher
                # Swallowed-but-accounted: the traceback logs ONCE per
                # handler (a broken handler throws on every event — one
                # stack is diagnosis, thousands are log spam) and every
                # occurrence lands in the
                # store_watch_handler_errors_total{kind} metric so a
                # silently-failing reconcile trigger is visible on a
                # dashboard instead of only in drowned logs.
                import logging

                from tf_operator_tpu.runtime import metrics

                self.error_count += 1
                metrics.store_watch_handler_errors.inc(kind=self.kind)
                logger = logging.getLogger("tpu_operator.store")
                if self.error_count == 1:
                    logger.exception(
                        "watch handler error for %s (first occurrence; "
                        "further ones are counted in "
                        "store_watch_handler_errors_total and logged "
                        "without traceback)", self.kind)
                else:
                    logger.warning(
                        "watch handler error for %s (%d so far)",
                        self.kind, self.error_count)

    def stop(self) -> None:
        # Deregister from the store first so _notify stops enqueueing
        # into a dead queue (unbounded growth otherwise).
        on_stop = getattr(self, "_on_stop", None)
        if on_stop is not None:
            on_stop(self)
        self.queue.put(None)


class Store:
    def __init__(self):
        self._lock = threading.RLock()
        # kind -> {(namespace, name) -> obj}
        self._objects: Dict[str, Dict[Tuple[str, str], object]] = {}
        self._watchers: List[Watcher] = []
        self._rv = itertools.count(1)
        # (kind, namespace, job-name label) -> {(ns, name), ...}
        self._label_index: Dict[Tuple[str, str, str], set] = {}
        # (kind, controller-owner uid) -> {(ns, name), ...}
        self._owner_index: Dict[Tuple[str, str], set] = {}

    # -- indexes (maintained under the lock on every write) ---------------

    def _index_add(self, kind: str, key: Tuple[str, str], obj) -> None:
        job_name = obj.metadata.labels.get(INDEX_LABEL_JOB_NAME)
        if job_name:
            self._label_index.setdefault(
                (kind, key[0], job_name), set()).add(key)
        ref = obj.metadata.controller_ref()
        if ref is not None and ref.uid:
            self._owner_index.setdefault((kind, ref.uid), set()).add(key)

    def _index_remove(self, kind: str, key: Tuple[str, str], obj) -> None:
        job_name = obj.metadata.labels.get(INDEX_LABEL_JOB_NAME)
        if job_name:
            bucket = self._label_index.get((kind, key[0], job_name))
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._label_index[(kind, key[0], job_name)]
        ref = obj.metadata.controller_ref()
        if ref is not None and ref.uid:
            bucket = self._owner_index.get((kind, ref.uid))
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._owner_index[(kind, ref.uid)]

    # -- CRUD -------------------------------------------------------------

    def create(self, kind: str, obj) -> object:
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            if key in coll:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            obj = obj.deepcopy()
            if not obj.metadata.uid:
                obj.metadata.uid = str(uuid.uuid4())
            if obj.metadata.creation_timestamp is None:
                obj.metadata.creation_timestamp = _dt.datetime.now(
                    _dt.timezone.utc)
            obj.metadata.resource_version = next(self._rv)
            coll[key] = obj
            self._index_add(kind, key, obj)
            self._notify(kind, ADDED, obj)
            return obj.deepcopy()

    def get(self, kind: str, namespace: str, name: str) -> object:
        with self._lock:
            try:
                return self._objects[kind][(namespace, name)].deepcopy()
            except KeyError:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")

    def try_get(self, kind: str, namespace: str, name: str):
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[object]:
        with self._lock:
            out = []
            for (ns, _), obj in self._objects.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                if selector and not matches_selector(obj.metadata.labels,
                                                     selector):
                    continue
                out.append(obj.deepcopy())
            return out

    def project(self, kind: str, fn, namespace: Optional[str] = None):
        """Read-only projection under the lock WITHOUT deepcopying:
        collects ``fn(obj)`` for every object, skipping ``None``
        results. ``fn`` must treat the object as frozen — no mutation,
        no retaining references past the call (the cheap-scan pattern
        of list_claimable, generalized; a full list() deepcopies every
        payload, which hot per-sync scans must not)."""
        out = []
        with self._lock:
            for (ns, _), obj in self._objects.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                v = fn(obj)
                if v is not None:
                    out.append(v)
        return out

    def list_claimable(self, kind: str, namespace: str,
                       selector: Dict[str, str],
                       owner_uid: str) -> List[object]:
        """Objects a controller's claim pass must see: label matches OR
        already owned by ``owner_uid`` (covers owned objects whose
        labels stopped matching, which release needs).

        O(owned): candidates come from the job-name-label and owner-UID
        indexes, so a namespace full of other jobs' pods costs nothing
        (pre-index this scanned — and a full list() deepcopied — every
        object in the namespace per job sync). Falls back to the scan
        only for selectors without the indexed label.

        Returns FROZEN shared snapshots, not copies: stored objects are
        never mutated in place (every write replaces the slot), so the
        only contract is on the caller — treat the result as immutable
        and ``deepcopy()`` any object before mutating it (the claim
        pass does exactly that on its rare adopt/release edges)."""
        with self._lock:
            coll = self._objects.get(kind, {})
            job_name = (selector or {}).get(INDEX_LABEL_JOB_NAME)
            if job_name is None:
                candidates = [k for k in coll if k[0] == namespace]
            else:
                keys = set(self._label_index.get(
                    (kind, namespace, job_name), ()))
                keys.update(self._owner_index.get((kind, owner_uid), ()))
                candidates = sorted(keys)  # deterministic sync order
            out = []
            for key in candidates:
                obj = coll.get(key)
                if obj is None or key[0] != namespace:
                    continue
                if not matches_selector(obj.metadata.labels, selector):
                    ref = obj.metadata.controller_ref()
                    if ref is None or ref.uid != owner_uid:
                        continue
                out.append(obj)
            return out

    def owned_keys(self, kind: str, owner_uid: str) -> List[Tuple[str, str]]:
        """(namespace, name) keys of objects whose controller
        ownerReference is ``owner_uid`` — O(owned) via the owner index,
        no payload copies. The garbage-collection primitive: cascade
        deletes used to re-list (and deepcopy) whole namespaces."""
        with self._lock:
            return sorted(self._owner_index.get((kind, owner_uid), ()))

    def update(self, kind: str, obj) -> object:
        """Full-object update with optimistic concurrency: the caller's
        resourceVersion must match the stored one."""
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            current = coll.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            if (obj.metadata.resource_version
                    and obj.metadata.resource_version
                    != current.metadata.resource_version):
                raise ConflictError(
                    f"{kind} {key}: resourceVersion "
                    f"{obj.metadata.resource_version} != "
                    f"{current.metadata.resource_version}")
            obj = obj.deepcopy()
            obj.metadata.uid = current.metadata.uid
            obj.metadata.creation_timestamp = current.metadata.creation_timestamp
            obj.metadata.resource_version = next(self._rv)
            self._index_remove(kind, key, current)
            coll[key] = obj
            self._index_add(kind, key, obj)
            self._notify(kind, MODIFIED, obj)
            return obj.deepcopy()

    def update_status(self, kind: str, obj) -> object:
        """Status-subresource-style update: merges only .status (and
        completion metadata) into the stored object, avoiding spec clobber."""
        with self._lock:
            coll = self._objects.setdefault(kind, {})
            key = (obj.metadata.namespace, obj.metadata.name)
            current = coll.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            stored = current.deepcopy()
            stored.status = obj.status.deepcopy()
            stored.metadata.resource_version = next(self._rv)
            # No index maintenance: a status merge cannot change the
            # labels/ownerRefs the (key-valued) indexes are built from.
            coll[key] = stored
            self._notify(kind, MODIFIED, stored)
            return stored.deepcopy()

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            coll = self._objects.get(kind, {})
            obj = coll.pop((namespace, name), None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            self._index_remove(kind, (namespace, name), obj)
            self._notify(kind, DELETED, obj)

    def try_delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self.delete(kind, namespace, name)
            return True
        except NotFoundError:
            return False

    def count(self, kind: str) -> int:
        """Object count without the deepcopy cost of list()."""
        with self._lock:
            return len(self._objects.get(kind, {}))

    def keys(self, kind: str) -> List[Tuple[str, str, int]]:
        """(namespace, name, resourceVersion) tuples without deepcopying
        payloads — for pruning/housekeeping over large collections."""
        with self._lock:
            return [(ns, name, obj.metadata.resource_version)
                    for (ns, name), obj in self._objects.get(kind, {}).items()]

    # -- watch ------------------------------------------------------------

    def watch(self, kind: str,
              handler: Callable[[str, object], None],
              replay: bool = True) -> Watcher:
        """Register a handler; with ``replay`` existing objects are
        delivered as ADDED first (informer initial list)."""
        with self._lock:
            w = Watcher(kind, handler)
            w._on_stop = self._remove_watcher
            if replay:
                for obj in self._objects.get(kind, {}).values():
                    w.queue.put((ADDED, obj.deepcopy()))
            self._watchers.append(w)
            return w

    def _remove_watcher(self, w: Watcher) -> None:
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass  # already removed (stop_watchers or double stop)

    def stop_watchers(self) -> None:
        with self._lock:
            watchers, self._watchers = self._watchers, []
        for w in watchers:
            w.stop()

    def _notify(self, kind: str, event_type: str, obj) -> None:
        for w in self._watchers:
            if w.kind == kind:
                w.queue.put((event_type, obj.deepcopy()))


# Canonical collection names.
TPUJOBS = "tpujobs"
PODS = "pods"
ENDPOINTS = "endpoints"
SLICEGROUPS = "slicegroups"
EVENTS = "events"
NODES = "nodes"
# Multi-tenant admission (controller/quota.py). TENANTQUEUES is
# namespaced; CLUSTERQUEUES is cluster-scoped (stored under the
# reserved namespace "").
TENANTQUEUES = "tenantqueues"
CLUSTERQUEUES = "clusterqueues"
# Checkpoint coordination (controller/ckpt.py): one record per replica,
# named after the pod, labeled job-name — the save-before-evict barrier's
# ack channel and the restore-step source.
CHECKPOINTRECORDS = "checkpointrecords"
