"""Operator metrics: counters/gauges/histograms + Prometheus exposition.

Reference parity: the promauto counters sprinkled through the reference
controller (pkg/controller.v1/tensorflow/job.go:29-36 jobs created/
deleted/restarted, status.go:47-61 successful/failed, pod.go:56-63
restarted pods, vendored common/pod.go:57-70 created/deleted pods,
common/service.go:36-45 service creations, common/job_controller.go:41-57
PodGroups, cmd/tf-operator.v1/app/server.go:65-69 is_leader gauge) and
the /metrics endpoint (cmd/tf-operator.v1/main.go:39-50). The catalog is
documented in docs/monitoring.md, mirroring the reference's
docs/monitoring/README.md.

No prometheus_client dependency: the registry renders the text
exposition format (v0.0.4) itself, which is all a scraper needs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], float] = {}

    kind = "untyped"

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.label_names)}")
        return tuple(labels[n] for n in self.label_names)

    def collect(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return sorted(self._children.items())

    def remove(self, **labels: str) -> None:
        """Drop one labeled child series so deleted objects stop
        occupying the exposition forever (job-labeled gauges are pruned
        by job GC — unbounded cardinality is a slow OOM on a
        long-running operator). No-op when the series never existed."""
        key = self._key(labels)
        with self._lock:
            self._children.pop(key, None)
            self._drop_child(key)

    def _drop_child(self, key: Tuple[str, ...]) -> None:
        """Subclass hook: drop per-child state beyond ``_children``."""

    def _render_labels(self, values: Tuple[str, ...]) -> str:
        if not self.label_names:
            return ""
        inner = ",".join(
            f'{n}="{_escape(v)}"' for n, v in zip(self.label_names, values))
        return "{" + inner + "}"

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        samples = self.collect()
        if not samples and not self.label_names:
            samples = [((), 0.0)]
        for values, v in samples:
            lines.append(f"{self.name}{self._render_labels(values)} {_fmt(v)}")
        return lines


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (used for reconcile + ready latency)."""

    kind = "histogram"

    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0, 30.0, 60.0)

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels: str) -> "_Timer":
        return _Timer(self, labels)

    def _drop_child(self, key: Tuple[str, ...]) -> None:
        self._counts.pop(key, None)
        self._sums.pop(key, None)
        self._totals.pop(key, None)

    def sum_value(self, **labels: str) -> float:
        """The series' cumulative _sum sample (benchmark artifacts read
        totals without scraping the exposition text)."""
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def count_value(self, **labels: str) -> int:
        """The series' cumulative _count sample."""
        with self._lock:
            return self._totals.get(self._key(labels), 0)

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """The ``q``-quantile (0 <= q <= 1) interpolated from the
        cumulative bucket counts — Prometheus ``histogram_quantile``
        semantics, computed locally so status/bench artifacts can report
        p50/p99 without a scrape+PromQL round trip:

        - linear interpolation inside the bucket the target rank lands
          in (lower bound = previous bucket's upper bound, 0.0 for the
          first bucket);
        - ranks falling in the +Inf overflow bucket clamp to the highest
          finite bound (the histogram cannot resolve beyond it);
        - None when the series has no observations.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        key = self._key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            if total == 0:
                return None
            counts = list(self._counts.get(key, ()))
        target = q * total
        prev_cum, lo = 0, 0.0
        for cum, hi in zip(counts, self.buckets):
            if cum >= target:
                in_bucket = cum - prev_cum
                frac = ((target - prev_cum) / in_bucket) if in_bucket else 1.0
                return lo + frac * (hi - lo)
            prev_cum, lo = cum, hi
        return float(self.buckets[-1])

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key in sorted(self._counts):
                counts = self._counts[key]
                for i, ub in enumerate(self.buckets):
                    labels = dict(zip(self.label_names, key))
                    labels["le"] = _fmt(ub)
                    inner = ",".join(f'{n}="{_escape(v)}"'
                                     for n, v in labels.items())
                    lines.append(
                        f"{self.name}_bucket{{{inner}}} {counts[i]}")
                base = self._render_labels(key)
                inf_labels = dict(zip(self.label_names, key))
                inf_labels["le"] = "+Inf"
                inner = ",".join(f'{n}="{_escape(v)}"'
                                 for n, v in inf_labels.items())
                lines.append(f"{self.name}_bucket{{{inner}}} "
                             f"{self._totals[key]}")
                lines.append(f"{self.name}_sum{base} "
                             f"{_fmt(self._sums[key])}")
                lines.append(f"{self.name}_count{base} {self._totals[key]}")
        return lines


class _Timer:
    def __init__(self, hist: Histogram, labels: Dict[str, str]):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)
        return False


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help_, labels))  # type: ignore

    def gauge(self, name: str, help_: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help_, labels))  # type: ignore

    def histogram(self, name: str, help_: str, labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._register(
            Histogram(name, help_, labels, buckets))  # type: ignore

    def render_text(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: List[str] = []
        for m in metrics:
            out.extend(m.render())
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        """Test helper: drop all recorded samples, keep registrations."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._children.clear()
                if isinstance(m, Histogram):
                    m._counts.clear()
                    m._sums.clear()
                    m._totals.clear()


REGISTRY = Registry()

# --- the catalog (docs/monitoring.md; names mirror the reference's) -------

jobs_created = REGISTRY.counter(
    "tpu_operator_jobs_created_total",
    "Counts number of TPU jobs created", ["job_namespace"])
jobs_deleted = REGISTRY.counter(
    "tpu_operator_jobs_deleted_total",
    "Counts number of TPU jobs deleted", ["job_namespace"])
jobs_successful = REGISTRY.counter(
    "tpu_operator_jobs_successful_total",
    "Counts number of TPU jobs successful", ["job_namespace"])
jobs_failed = REGISTRY.counter(
    "tpu_operator_jobs_failed_total",
    "Counts number of TPU jobs failed", ["job_namespace"])
jobs_restarted = REGISTRY.counter(
    "tpu_operator_jobs_restarted_total",
    "Counts number of TPU jobs restarted", ["job_namespace"])
created_pods = REGISTRY.counter(
    "tpu_operator_created_pods_total",
    "Counts number of pods created by the operator", ["job_namespace"])
deleted_pods = REGISTRY.counter(
    "tpu_operator_deleted_pods_total",
    "Counts number of pods deleted by the operator", ["job_namespace"])
restarted_pods = REGISTRY.counter(
    "tpu_operator_restarted_pods_total",
    "Counts number of pods restarted with identity", ["job_namespace"])
created_endpoints = REGISTRY.counter(
    "tpu_operator_created_endpoints_total",
    "Counts number of per-replica endpoints created", ["job_namespace"])
deleted_endpoints = REGISTRY.counter(
    "tpu_operator_deleted_endpoints_total",
    "Counts number of per-replica endpoints deleted", ["job_namespace"])
slicegroups_created = REGISTRY.counter(
    "tpu_operator_slicegroups_created_total",
    "Counts number of gang SliceGroups created", ["job_namespace"])
slicegroups_deleted = REGISTRY.counter(
    "tpu_operator_slicegroups_deleted_total",
    "Counts number of gang SliceGroups deleted", ["job_namespace"])
slicegroups_preempted = REGISTRY.counter(
    "tpu_operator_slicegroups_preempted_total",
    "Counts gang SliceGroups evicted back to Pending by higher-priority "
    "admission", ["job_namespace"])
gang_pods_bound = REGISTRY.counter(
    "tpu_operator_gang_pods_bound_total",
    "Counts pods the in-operator slice-gang binder bound to nodes",
    ["job_namespace"])
slice_drains = REGISTRY.counter(
    "tpu_operator_slice_drains_total",
    "Counts gang SliceGroups atomically drained off degraded nodes by "
    "the slice-health controller", ["job_namespace"])
nodes_cordoned = REGISTRY.counter(
    "tpu_operator_nodes_cordoned_total",
    "Counts nodes the slice-health controller cordoned on degradation "
    "signals", ["reason"])
drain_rebind_seconds = REGISTRY.histogram(
    "tpu_operator_drain_rebind_seconds",
    "Gang drain to fully-rebound-on-spare-capacity latency (slice-health "
    "auto-repair)", ["job_namespace"],
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
             300.0))
kube_client_throttled = REGISTRY.counter(
    "tpu_operator_kube_client_throttled_total",
    "Counts 429 responses the kube client honored (slept Retry-After "
    "and retried)")
is_leader = REGISTRY.gauge(
    "tpu_operator_is_leader",
    "1 while this operator replica holds the leader lease")
reconcile_seconds = REGISTRY.histogram(
    "tpu_operator_reconcile_duration_seconds",
    "Wall time of one job reconcile")
ready_latency_seconds = REGISTRY.histogram(
    "tpu_operator_all_replicas_ready_seconds",
    "Job creation to all-replicas-Running latency (BASELINE north star)",
    ["job_namespace"],
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0))
workqueue_depth = REGISTRY.gauge(
    "tpu_operator_workqueue_depth",
    "Items waiting in the controller workqueue")
workqueue_coalesced = REGISTRY.counter(
    "tpu_operator_workqueue_coalesced_total",
    "Enqueues coalesced into an already-pending key (event storms "
    "collapsed into one sync)")
workqueue_latency_seconds = REGISTRY.histogram(
    "tpu_operator_workqueue_latency_seconds",
    "Enqueue-to-dequeue wait of workqueue items (sync scheduling "
    "latency)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
             2.5, 5.0, 10.0, 30.0))
events_aggregated = REGISTRY.counter(
    "tpu_operator_events_aggregated_total",
    "Recorder events folded into an existing event (duplicate count "
    "bump or EventAggregator-style similar-event collapse) instead of "
    "stored/posted individually")
queue_pending_slices = REGISTRY.gauge(
    "tpu_operator_queue_pending_slices",
    "SliceGroups of a tenant queue waiting for quota or capacity",
    ["queue"])
queue_admitted_chips = REGISTRY.gauge(
    "tpu_operator_queue_admitted_chips",
    "Chips currently admitted through a ClusterQueue", ["queue"])
queue_borrowed_chips = REGISTRY.gauge(
    "tpu_operator_queue_borrowed_chips",
    "Portion of a ClusterQueue's admitted chips above its nominal quota "
    "(borrowed from idle cohort capacity)", ["queue"])
quota_reclaims = REGISTRY.counter(
    "tpu_operator_quota_reclaims_total",
    "Borrowed gangs displaced back to Pending so a cohort member could "
    "take its nominal quota back", ["queue"])
queue_admission_wait_seconds = REGISTRY.histogram(
    "tpu_operator_queue_admission_wait_seconds",
    "Pending to quota-admitted wait of gang SliceGroups, per tenant "
    "queue", ["queue"],
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
             300.0, 600.0, 1800.0))
checkpoint_save_seconds = REGISTRY.histogram(
    "tpu_operator_checkpoint_save_seconds",
    "Wall time of one replica checkpoint save, as reported through "
    "CheckpointRecords (periodic saves and barrier-forced saves alike)",
    ["job_namespace"],
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0))
checkpoint_barrier_acks = REGISTRY.counter(
    "tpu_operator_checkpoint_barrier_acks_total",
    "Per-replica save acks received inside save-before-evict barriers",
    ["job_namespace"])
checkpoint_barriers = REGISTRY.counter(
    "tpu_operator_checkpoint_barriers_total",
    "Save-before-evict barriers completed, by outcome (acked = every "
    "required replica saved; timeout = evicted at the deadline)",
    ["job_namespace", "outcome"])
steps_lost_per_disruption = REGISTRY.histogram(
    "tpu_operator_steps_lost_per_disruption",
    "Training steps lost to one planned disruption: last reported "
    "progress minus the step the barrier committed",
    ["job_namespace"],
    buckets=(0.0, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0, 5000.0))
job_goodput_ratio = REGISTRY.gauge(
    "tpu_operator_job_goodput_ratio",
    "Fraction of a job's training steps NOT lost to disruptions: "
    "(progress - cumulative steps lost) / progress, 1.0 until the "
    "first loss", ["job_namespace", "job"])
learner_goodput_ratio = REGISTRY.gauge(
    "tpu_operator_learner_goodput_ratio",
    "job_goodput_ratio restricted to heterogeneous (RolePolicy) jobs: "
    "fraction of the LEARNER gang's steps not lost to disruptions. "
    "Actor-only churn must not move it — that invariant is the point "
    "of the actor/learner split (docs/rl.md)", ["job_namespace", "job"])
actor_pool_replicas = REGISTRY.gauge(
    "tpu_operator_actor_pool_replicas",
    "Current replica count of an elastic RolePolicy role (an RL actor "
    "pool), updated at every applied role resize (docs/rl.md)",
    ["job_namespace", "job", "replica_type"])
actor_preemptions = REGISTRY.counter(
    "tpu_operator_actor_preemptions_total",
    "Evict-class (non-barrier) replicas evicted without a "
    "save-before-evict barrier, by reason (health|chaos|manual): the "
    "disruptions the learner gang is supposed to ride out (docs/rl.md)",
    ["job_namespace", "reason"])
gang_resizes = REGISTRY.counter(
    "tpu_operator_gang_resizes_total",
    "Elastic gang resizes applied by the control plane, by direction "
    "(grow|shrink) and reason (idle|reclaim|drain|manual|chaos|"
    "autoscale)",
    ["direction", "reason"])
job_slices = REGISTRY.gauge(
    "tpu_operator_job_slices",
    "Current slice count of an elastic gang, updated at every applied "
    "resize (docs/elastic.md)", ["job_namespace", "job"])
resize_barrier_seconds = REGISTRY.histogram(
    "tpu_operator_resize_barrier_seconds",
    "Shrink decision to save-barrier release: how long an elastic "
    "shrink waited for the gang's final checkpoint acks before the "
    "smaller world was rendered", ["job_namespace"],
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0))
api_retries = REGISTRY.counter(
    "tpu_operator_api_retries_total",
    "In-place retries of transient API failures (runtime/retry.py "
    "with_retries backoff), by the retrying component", ["component"])
controlplane_degraded = REGISTRY.gauge(
    "tpu_operator_controlplane_degraded",
    "1 while the API server has been failing past the degraded-mode "
    "threshold: the controller keeps reconciling but defers new "
    "drains/reclaims/preemptions (docs/robustness.md)")
degraded_entries = REGISTRY.counter(
    "tpu_operator_controlplane_degraded_entries_total",
    "Times the controller entered degraded mode (API server "
    "unreachable past the threshold)")
disruptions_deferred = REGISTRY.counter(
    "tpu_operator_disruptions_deferred_total",
    "Disruptive actions (drain/reclaim/preemption) NOT initiated "
    "because the control plane was degraded", ["action"])
store_watch_handler_errors = REGISTRY.counter(
    "tpu_operator_store_watch_handler_errors_total",
    "Exceptions raised by store watch handlers (swallowed so the "
    "dispatcher survives; traceback logged once per handler)", ["kind"])
bind_failures = REGISTRY.counter(
    "tpu_operator_bind_failures_total",
    "pods/binding POSTs that failed and will retry next binder pass, "
    "by failure category", ["reason"])
chaos_faults_injected = REGISTRY.counter(
    "tpu_operator_chaos_faults_injected_total",
    "Faults the chaos layer injected (runtime/chaos.py FaultProfile; "
    "test/bench harnesses only — always 0 in production)", ["fault"])
node_agent_heartbeats = REGISTRY.counter(
    "tpu_operator_node_agent_heartbeats_total",
    "Heartbeats a node agent successfully published to the control "
    "plane (served: NodeStatus.last_heartbeat write; kube: "
    "agent-heartbeat annotation PATCH)", ["node"])
node_agent_relay_errors = REGISTRY.counter(
    "tpu_operator_node_agent_relay_errors_total",
    "Node-agent relay operations that failed after retries, by kind "
    "(notice_write = preemption notice file, ckpt_read = worker "
    "checkpoint state file, ckpt_patch = ckpt-state annotation PATCH)",
    ["kind"])
trace_spans_dropped = REGISTRY.counter(
    "tpu_operator_trace_spans_dropped_total",
    "Spans of completed traces the flight recorder did NOT retain "
    "(neither slowest-N, errored, nor the sample ring — "
    "docs/observability.md); phase totals still count them")

# --- sharded control plane (runtime/leaderelection.py ShardMap,
# runtime/store.py watch log / pagination; docs/benchmarks.md).
shard_owner = REGISTRY.gauge(
    "tpu_operator_shard_owner",
    "1 while this replica holds the lease for control-plane shard "
    "<shard> (tpu-operator-shard-<i>); 0 after a release or stepdown",
    ["shard"])
shard_reassignments = REGISTRY.counter(
    "tpu_operator_shard_reassignments_total",
    "Shard leases this replica took over from another holder (lease "
    "transitions observed at acquire time — failover adoptions, not "
    "first acquisitions)")
watch_cache_hits = REGISTRY.counter(
    "tpu_operator_watch_cache_hits_total",
    "Watch registrations resumed from the store's per-kind event log "
    "(resourceVersion known and still in the log) instead of a full "
    "ADDED replay of every stored object", ["kind"])
list_pages = REGISTRY.counter(
    "tpu_operator_list_pages_total",
    "Paginated list pages served from the store (continue-token keyset "
    "walks; each page returns frozen snapshots, no payload deepcopy)",
    ["kind"])

# --- serving plane (tf_operator_tpu/serve; docs/serving.md SLO catalog).
# Observed by the ServingEngine in whichever process runs it: each
# serving replica exposes its own /metrics in production; benchmarks and
# in-process tests read the ambient registry directly.
serving_tokens_per_second = REGISTRY.gauge(
    "tpu_operator_serving_tokens_per_second",
    "Decode throughput of this serving replica over the last engine "
    "step window (generated tokens only; prompt tokens excluded)")
serving_ttft_seconds = REGISTRY.histogram(
    "tpu_operator_serving_ttft_seconds",
    "Time to first token: request enqueue to the prefill that emitted "
    "its first generated token (the serving SLO's head latency; p50/p99 "
    "via Histogram.quantile)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0))
serving_queue_depth = REGISTRY.gauge(
    "tpu_operator_serving_queue_depth",
    "Requests waiting in a tenant's QoS lane of the serving request "
    "queue (admitted-to-slot requests excluded)", ["tenant"])
serving_requests_total = REGISTRY.counter(
    "tpu_operator_serving_requests_total",
    "Serving requests by terminal outcome: completed (response "
    "emitted), rejected (queue full at submit), requeued (drained "
    "mid-flight back to the spool for another replica)", ["outcome"])
gateway_requests = REGISTRY.counter(
    "tpu_operator_gateway_requests_total",
    "HTTP requests the serving gateway answered, by status code (200 "
    "accepted+streamed, 400 malformed, 401 unknown auth token, 429 "
    "spool backlog at maxQueueDepth — carries Retry-After)", ["code"])
gateway_streaming_seconds = REGISTRY.histogram(
    "tpu_operator_gateway_streaming_seconds",
    "Accepted gateway request admission to last streamed token (the "
    "full-response latency the TTFT histogram is the head of)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0))

# --- serving replica autoscaler (controller/autoscaler.py;
# docs/serving.md autoscaler section).
autoscaler_target_slices = REGISTRY.gauge(
    "tpu_operator_autoscaler_target_slices",
    "The autoscaler's most recent numSlices target for a serving gang "
    "(post-clamp to minSlices/maxSlices; compare with job_slices to "
    "see convergence)", ["job_namespace", "job"])
autoscaler_holds = REGISTRY.counter(
    "tpu_operator_autoscaler_holds_total",
    "Autoscaler passes that wanted a different size but held, by "
    "reason (cooldown = shrink hysteresis window still open; settling "
    "= a prior resize has not completed; bounds = target clamped back "
    "to the current size)", ["reason"])
