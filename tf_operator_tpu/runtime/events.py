"""Event recorder (reference: client-go record.EventRecorder wired at
job_controller.go:158-162; events are emitted on every lifecycle edge).

Storm control (client-go EventCorrelator/EventAggregator analog): an
exact duplicate within the aggregation window bumps the stored event's
``count`` instead of appending — and once more than
``SIMILAR_EVENTS_THRESHOLD`` events share (kind, name, type, reason)
in the window, further ones collapse into a single "(combined from
similar events)" record. Either way the fan-out sink is NOT re-invoked,
so a 256-pod gang storm doesn't become 256 API Event writes in the kube
backend (kube.py _post_event) or 256 store writes in the local one.
"""

from __future__ import annotations

import datetime as _dt
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.runtime import metrics

log = logging.getLogger("tpu_operator.events")

# Aggregation window + similar-event threshold (client-go defaults are
# 10 minutes / 10 events; same here).
AGGREGATION_WINDOW_SECONDS = 600.0
SIMILAR_EVENTS_THRESHOLD = 10

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

# Slice-health / auto-repair event reasons (controller/health.py) — the
# drain/rebind lifecycle's observable edges, named here so emitters and
# test/SDK consumers share one vocabulary.
REASON_NODE_CORDONED = "NodeCordoned"
REASON_SLICE_DRAIN_PENDING = "SliceDrainPending"
REASON_SLICE_DRAINED = "SliceDrained"
REASON_SLICE_REBOUND = "SliceRebound"

# Checkpoint-coordination event reasons (controller/ckpt.py) — the
# save-before-evict barrier's observable edges.
REASON_CKPT_BARRIER_REQUESTED = "CheckpointBarrierRequested"
REASON_CKPT_BARRIER_SAVED = "CheckpointBarrierSaved"
REASON_CKPT_BARRIER_TIMEOUT = "CheckpointBarrierTimeout"

# Tenant-queue quota event reasons (controller/quota.py) — the
# quota-admission lifecycle's observable edges.
REASON_QUEUED_WAITING_FOR_QUOTA = "QueuedWaitingForQuota"
REASON_QUOTA_EXCEEDED = "QuotaExceeded"
REASON_BORROWED_CAPACITY = "BorrowedCapacity"
REASON_QUOTA_RECLAIMED = "QuotaReclaimed"
REASON_QUEUE_DELETED = "QueueDeleted"

# Elastic-gang event reasons (controller/gang.py resize pass,
# docs/elastic.md) — one event per applied grow/shrink.
REASON_GANG_RESIZED = "GangResized"

# Heterogeneous-gang event reasons (docs/rl.md): evict-class replicas
# (RL actors) removed from a degraded node WITHOUT a barrier or a gang
# drain — the learner world keeps running.
REASON_ACTOR_EVICTED = "ActorEvicted"


@dataclass
class Event:
    object_kind: str
    object_name: str
    namespace: str
    type: str
    reason: str
    message: str
    timestamp: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc))
    # The involved object's labels (job-name etc.) so sinks can attribute
    # pod events to their job without name parsing.
    labels: dict = field(default_factory=dict)
    # How many occurrences this record stands for (aggregation).
    count: int = 1


class Recorder:
    """In-memory event sink with optional fan-out callback and
    EventCorrelator-style duplicate/similar aggregation."""

    def __init__(self, sink: Optional[Callable[[Event], None]] = None,
                 max_events: int = 4096,
                 aggregation_window: float = AGGREGATION_WINDOW_SECONDS,
                 similar_threshold: int = SIMILAR_EVENTS_THRESHOLD):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._sink = sink
        self._max = max_events
        self._window = aggregation_window
        self._similar_threshold = similar_threshold
        # exact (kind, ns, name, type, reason, message) -> (event, last_seen)
        self._by_exact: Dict[Tuple, Tuple[Event, float]] = {}
        # similar (kind, ns, name, type, reason) -> (count, window_start,
        #                                            aggregate event | None)
        self._by_similar: Dict[Tuple, Tuple[int, float, Optional[Event]]] = {}

    def _aggregate(self, ev: Event, now: float) -> bool:
        """Fold ``ev`` into an existing record when it's a duplicate or
        part of a similar-event storm; returns True when folded (caller
        skips append + sink). Caller holds the lock."""
        similar_key = (ev.object_kind, ev.namespace, ev.object_name,
                       ev.type, ev.reason)
        exact_key = similar_key + (ev.message,)
        hit = self._by_exact.get(exact_key)
        if hit is not None and now - hit[1] <= self._window:
            record = hit[0]
            record.count += 1
            record.timestamp = ev.timestamp
            self._by_exact[exact_key] = (record, now)
            metrics.events_aggregated.inc()
            return True
        n, start, aggregate = self._by_similar.get(similar_key,
                                                   (0, now, None))
        if now - start > self._window:
            n, start, aggregate = 0, now, None
        n += 1
        if n > self._similar_threshold:
            if aggregate is None:
                aggregate = Event(
                    object_kind=ev.object_kind, object_name=ev.object_name,
                    namespace=ev.namespace, type=ev.type, reason=ev.reason,
                    message=f"(combined from similar events): {ev.message}",
                    labels=dict(ev.labels), count=n)
                self._events.append(aggregate)
            else:
                aggregate.count = n
                aggregate.message = ("(combined from similar events): "
                                     f"{ev.message}")
                aggregate.timestamp = ev.timestamp
            self._by_similar[similar_key] = (n, start, aggregate)
            metrics.events_aggregated.inc()
            return True
        self._by_similar[similar_key] = (n, start, aggregate)
        self._by_exact[exact_key] = (ev, now)
        return False

    def event(self, obj, etype: str, reason: str, message: str) -> None:
        meta = getattr(obj, "metadata", None)
        ev = Event(
            object_kind=getattr(obj, "kind", type(obj).__name__),
            object_name=getattr(meta, "name", "") if meta else "",
            namespace=getattr(meta, "namespace", "") if meta else "",
            type=etype, reason=reason, message=message,
            labels=dict(getattr(meta, "labels", None) or {}) if meta else {},
        )
        log.debug("%s %s %s/%s: %s", etype, reason, ev.namespace,
                  ev.object_name, message)
        with self._lock:
            if self._aggregate(ev, time.monotonic()):
                return  # folded into an existing record; no re-sink
            self._events.append(ev)
            if len(self._events) > self._max:
                self._events = self._events[-self._max:]
        if self._sink:
            self._sink(ev)

    def events_for(self, name: str = "", reason: str = "") -> List[Event]:
        with self._lock:
            return [e for e in self._events
                    if (not name or e.object_name == name)
                    and (not reason or e.reason == reason)]

    @property
    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)
