"""Event recorder (reference: client-go record.EventRecorder wired at
job_controller.go:158-162; events are emitted on every lifecycle edge)."""

from __future__ import annotations

import datetime as _dt
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

log = logging.getLogger("tpu_operator.events")

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"

# Slice-health / auto-repair event reasons (controller/health.py) — the
# drain/rebind lifecycle's observable edges, named here so emitters and
# test/SDK consumers share one vocabulary.
REASON_NODE_CORDONED = "NodeCordoned"
REASON_SLICE_DRAIN_PENDING = "SliceDrainPending"
REASON_SLICE_DRAINED = "SliceDrained"
REASON_SLICE_REBOUND = "SliceRebound"


@dataclass
class Event:
    object_kind: str
    object_name: str
    namespace: str
    type: str
    reason: str
    message: str
    timestamp: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc))
    # The involved object's labels (job-name etc.) so sinks can attribute
    # pod events to their job without name parsing.
    labels: dict = field(default_factory=dict)


class Recorder:
    """In-memory event sink with optional fan-out callback."""

    def __init__(self, sink: Optional[Callable[[Event], None]] = None,
                 max_events: int = 4096):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._sink = sink
        self._max = max_events

    def event(self, obj, etype: str, reason: str, message: str) -> None:
        meta = getattr(obj, "metadata", None)
        ev = Event(
            object_kind=getattr(obj, "kind", type(obj).__name__),
            object_name=getattr(meta, "name", "") if meta else "",
            namespace=getattr(meta, "namespace", "") if meta else "",
            type=etype, reason=reason, message=message,
            labels=dict(getattr(meta, "labels", None) or {}) if meta else {},
        )
        log.debug("%s %s %s/%s: %s", etype, reason, ev.namespace,
                  ev.object_name, message)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._max:
                self._events = self._events[-self._max:]
        if self._sink:
            self._sink(ev)

    def events_for(self, name: str = "", reason: str = "") -> List[Event]:
        with self._lock:
            return [e for e in self._events
                    if (not name or e.object_name == name)
                    and (not reason or e.reason == reason)]

    @property
    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)
