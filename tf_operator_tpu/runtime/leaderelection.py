"""Leader election: lease-based, exactly-one-active-reconciler.

Reference parity: cmd/tf-operator.v1/app/server.go:146-193 —
client-go leaderelection.RunOrDie over a resourcelock.EndpointsLock
("tf-operator" in the operator namespace) with LeaseDuration 15s,
RenewDeadline 5s, RetryPeriod 3s; OnStartedLeading runs the controller,
OnStoppedLeading fatals; the tf_operator_is_leader gauge flips at
server.go:65-69 and :175-182.

TPU-native shape: the lock record is a Lease object in the object store
(status-subresource-free, optimistic-concurrency CAS on update). With a
K8s backend the same protocol maps onto coordination.k8s.io/v1 Lease.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import hashlib
import logging
import threading
import uuid
from typing import Callable, Dict, List, Optional, Set

from tf_operator_tpu.api.types import ApiObject, ObjectMeta
from tf_operator_tpu.runtime import metrics as metrics_mod
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.metrics import is_leader as is_leader_gauge
from tf_operator_tpu.runtime.store import Store

log = logging.getLogger("tpu_operator.leaderelection")

LEASES = "leases"
DEFAULT_LOCK_NAME = "tpu-operator"


def shard_for(namespace: str, uid: str, shards: int) -> int:
    """Stable job->shard assignment: sha1 over (namespace, uid). Every
    replica computes the same mapping with no coordination; a job never
    migrates between shards for its lifetime (uid is immutable), so two
    shard holders can never both believe they own it."""
    if shards <= 1:
        return 0
    digest = hashlib.sha1(f"{namespace}/{uid}".encode()).digest()
    return int.from_bytes(digest[:4], "big") % shards


def shard_lock_name(index: int) -> str:
    """Lease name for control-plane shard ``index``."""
    return f"{DEFAULT_LOCK_NAME}-shard-{index}"


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclasses.dataclass
class LeaseSpec(ApiObject):
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: Optional[_dt.datetime] = None
    renew_time: Optional[_dt.datetime] = None
    lease_transitions: int = 0


@dataclasses.dataclass
class _EmptyStatus(ApiObject):
    pass


@dataclasses.dataclass
class Lease(ApiObject):
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: LeaseSpec = dataclasses.field(default_factory=LeaseSpec)
    status: _EmptyStatus = dataclasses.field(default_factory=_EmptyStatus)


class LeaderElector:
    """Acquire-then-renew loop. ``on_started_leading`` runs (once) in a
    daemon thread after acquisition; ``on_stopped_leading`` fires if a
    renewal misses the deadline (the reference fatals there)."""

    def __init__(self, store: Store,
                 identity: Optional[str] = None,
                 namespace: str = "default",
                 name: str = DEFAULT_LOCK_NAME,
                 lease_duration: float = 15.0,
                 renew_deadline: float = 5.0,
                 retry_period: float = 3.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        self.store = store
        self.identity = identity or f"{DEFAULT_LOCK_NAME}-{uuid.uuid4().hex[:8]}"
        self.namespace = namespace
        self.name = name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._leading = threading.Event()

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    def wait_until_leading(self, timeout: Optional[float] = None) -> bool:
        return self._leading.wait(timeout)

    # -- lock record CAS -------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        try:
            return self._acquire_or_renew_once()
        except Exception:
            # ANY failure — a 5xx burst, a timeout, a dropped
            # connection — is a failed attempt, never a thread-killer:
            # before this guard, a transient error here propagated out
            # of run(), silently killing the elector thread with
            # _leading still set — a zombie leader that never renews,
            # never steps down, and blocks standby failover until the
            # humans notice (found by the injected-renew-failure tests,
            # tests/test_leaderelection.py). The caller's retry loop +
            # renew deadline turn persistent failure into a clean
            # stepdown.
            log.warning("lease acquire/renew attempt failed; retrying",
                        exc_info=True)
            return False

    def _acquire_or_renew_once(self) -> bool:
        now = _now()
        lease = self.store.try_get(LEASES, self.namespace, self.name)
        if lease is None:
            fresh = Lease(spec=LeaseSpec(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now))
            fresh.metadata.name = self.name
            fresh.metadata.namespace = self.namespace
            try:
                self.store.create(LEASES, fresh)
                return True
            except store_mod.AlreadyExistsError:
                return False

        if lease.spec.holder_identity != self.identity:
            renew = lease.spec.renew_time
            expired = (renew is None or
                       (now - renew).total_seconds()
                       > lease.spec.lease_duration_seconds)
            if not expired:
                return False
            lease.spec.lease_transitions += 1
            lease.spec.acquire_time = now
            log.info("%s taking over expired lease from %s", self.identity,
                     lease.spec.holder_identity)

        lease.spec.holder_identity = self.identity
        lease.spec.renew_time = now
        lease.spec.lease_duration_seconds = self.lease_duration
        try:
            # Optimistic CAS: resource_version mismatch = lost the race.
            self.store.update(LEASES, lease)
            return True
        except (store_mod.ConflictError, store_mod.NotFoundError):
            return False

    def release(self) -> None:
        """Voluntarily drop the lease so a standby takes over instantly.
        Best-effort: on any failure (including transport errors during
        shutdown) the lease simply expires on its own duration."""
        try:
            lease = self.store.try_get(LEASES, self.namespace, self.name)
            if (lease is not None
                    and lease.spec.holder_identity == self.identity):
                lease.spec.holder_identity = ""
                lease.spec.renew_time = None
                self.store.update(LEASES, lease)
        except Exception:
            log.debug("lease release failed; it will expire on its own",
                      exc_info=True)

    # -- run loop --------------------------------------------------------

    def run(self) -> None:
        """Blocks until elected, then renews until stop() or lost lease."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            if self._stop.wait(self.retry_period):
                return
        if self._stop.is_set():
            return

        log.info("%s became leader", self.identity)
        self._leading.set()
        is_leader_gauge.set(1)
        if self.on_started_leading is not None:
            threading.Thread(target=self.on_started_leading,
                             name="leading", daemon=True).start()

        renew_every = min(self.renew_deadline / 2.0, 2.0)
        while not self._stop.wait(renew_every):
            deadline = _now() + _dt.timedelta(seconds=self.renew_deadline)
            renewed = False
            while _now() < deadline and not self._stop.is_set():
                if self._try_acquire_or_renew():
                    renewed = True
                    break
                self._stop.wait(min(self.retry_period, 0.5))
            if not renewed:
                log.error("%s failed to renew lease; stepping down",
                          self.identity)
                break
        self._leading.clear()
        is_leader_gauge.set(0)
        if not self._stop.is_set() and self.on_stopped_leading is not None:
            self.on_stopped_leading()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="leaderelect",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._leading.clear()
        is_leader_gauge.set(0)
        # Callable from the elector's own thread (on_stopped_leading →
        # shutdown paths); a thread cannot join itself.
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        self.release()


class ShardMap:
    """N-leader job ownership: one Lease per control-plane shard
    (``tpu-operator-shard-<i>``), each contended independently with the
    singleton LeaderElector protocol. Jobs hash to shards via
    :func:`shard_for`; the holder of shard i runs a full engine over
    only that shard's jobs.

    A replica contends for EVERY shard by default (so one replica can
    own the whole map — the single-process degenerate case) or for one
    pinned shard (``shard_index``). Failover needs no new protocol: a
    dead holder's lease expires and a survivor's elector takes it over;
    ``on_shard_acquired``/``on_shard_lost`` fire per shard so the
    caller builds and tears down the shard-scoped engine.

    Unlike the singleton elector (whose run() returns after stepdown —
    the reference fatals there), a shard loop RE-CONTENDS after losing:
    shard ownership is a pool, not a process lifetime.
    """

    def __init__(self, store: Store, shards: int,
                 identity: Optional[str] = None,
                 namespace: str = "default",
                 shard_index: Optional[int] = None,
                 lease_duration: float = 15.0,
                 renew_deadline: float = 5.0,
                 retry_period: float = 3.0,
                 on_shard_acquired: Optional[Callable[[int], None]] = None,
                 on_shard_lost: Optional[Callable[[int], None]] = None):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        if shard_index is not None and not 0 <= shard_index < shards:
            raise ValueError(
                f"shard_index {shard_index} out of range [0, {shards})")
        self.store = store
        self.shards = shards
        self.identity = (identity
                         or f"{DEFAULT_LOCK_NAME}-{uuid.uuid4().hex[:8]}")
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_shard_acquired = on_shard_acquired
        self.on_shard_lost = on_shard_lost
        # Shards this replica contends for (all, unless pinned).
        self._targets: List[int] = ([shard_index] if shard_index is not None
                                    else list(range(shards)))
        self._stop = threading.Event()
        self._held: Set[int] = set()
        self._held_lock = threading.Lock()
        self._crashed: Set[int] = set()
        self._electors: Dict[int, LeaderElector] = {}
        self._threads: List[threading.Thread] = []
        # Takeovers of a previously-held lease observed at acquire time
        # (mirrors tpu_operator_shard_reassignments_total for benches).
        self.reassignments = 0
        self._transitions_seen: Dict[int, int] = {}

    def held(self) -> Set[int]:
        with self._held_lock:
            return set(self._held)

    def is_held(self, index: int) -> bool:
        with self._held_lock:
            return index in self._held

    def wait_until_held(self, count: int,
                        timeout: Optional[float] = None) -> bool:
        """Block until this replica holds at least ``count`` shards."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while len(self.held()) < count:
            if deadline is not None and _time.monotonic() > deadline:
                return False
            if self._stop.wait(0.02):
                return False
        return True

    # -- per-shard contention loop ---------------------------------------

    def _shard_loop(self, index: int) -> None:
        while not self._stop.is_set() and index not in self._crashed:
            elector = LeaderElector(
                self.store, identity=self.identity,
                namespace=self.namespace, name=shard_lock_name(index),
                lease_duration=self.lease_duration,
                renew_deadline=self.renew_deadline,
                retry_period=self.retry_period,
                on_started_leading=lambda i=index: self._acquired(i),
                on_stopped_leading=lambda i=index: self._lost(i))
            self._electors[index] = elector
            elector.run()  # blocks: acquire -> renew -> stepdown/stop

    def _acquired(self, index: int) -> None:
        with self._held_lock:
            self._held.add(index)
        metrics_mod.shard_owner.set(1, shard=str(index))
        lease = self.store.try_get(LEASES, self.namespace,
                                   shard_lock_name(index))
        transitions = 0 if lease is None else lease.spec.lease_transitions
        if transitions > self._transitions_seen.get(index, 0):
            # The lease changed hands to get here — a failover
            # adoption, not a first acquisition.
            self.reassignments += 1
            metrics_mod.shard_reassignments.inc()
        self._transitions_seen[index] = transitions
        log.info("shard %d/%d acquired by %s (lease transitions: %d)",
                 index, self.shards, self.identity, transitions)
        if self.on_shard_acquired is not None:
            self.on_shard_acquired(index)

    def _lost(self, index: int) -> None:
        with self._held_lock:
            self._held.discard(index)
        metrics_mod.shard_owner.set(0, shard=str(index))
        log.warning("shard %d lost by %s; re-contending", index,
                    self.identity)
        if self.on_shard_lost is not None:
            self.on_shard_lost(index)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        for i in self._targets:
            t = threading.Thread(target=self._shard_loop, args=(i,),
                                 name=f"shard-elect-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def crash(self, index: int) -> None:
        """Simulate holder death for one shard: stop renewing WITHOUT
        releasing the lease and WITHOUT firing on_shard_lost — exactly
        what a killed process leaves behind. A survivor must wait out
        the lease duration before adopting (availability cost), and the
        caller is responsible for abandoning the shard's engine (e.g.
        chaos.crash_controller). stop() is the graceful counterpart."""
        self._crashed.add(index)
        elector = self._electors.get(index)
        if elector is not None:
            elector.on_stopped_leading = None
            elector._stop.set()
            elector._leading.clear()
        with self._held_lock:
            self._held.discard(index)
        metrics_mod.shard_owner.set(0, shard=str(index))

    def stop(self) -> None:
        """Graceful stop: release every held lease so standbys take
        over instantly. on_shard_lost does NOT fire (the caller is
        tearing everything down itself)."""
        self._stop.set()
        for elector in list(self._electors.values()):
            elector.stop()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5)
        with self._held_lock:
            held, self._held = set(self._held), set()
        for i in held:
            metrics_mod.shard_owner.set(0, shard=str(i))
