"""Leader election: lease-based, exactly-one-active-reconciler.

Reference parity: cmd/tf-operator.v1/app/server.go:146-193 —
client-go leaderelection.RunOrDie over a resourcelock.EndpointsLock
("tf-operator" in the operator namespace) with LeaseDuration 15s,
RenewDeadline 5s, RetryPeriod 3s; OnStartedLeading runs the controller,
OnStoppedLeading fatals; the tf_operator_is_leader gauge flips at
server.go:65-69 and :175-182.

TPU-native shape: the lock record is a Lease object in the object store
(status-subresource-free, optimistic-concurrency CAS on update). With a
K8s backend the same protocol maps onto coordination.k8s.io/v1 Lease.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
import threading
import uuid
from typing import Callable, Optional

from tf_operator_tpu.api.types import ApiObject, ObjectMeta
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.metrics import is_leader as is_leader_gauge
from tf_operator_tpu.runtime.store import Store

log = logging.getLogger("tpu_operator.leaderelection")

LEASES = "leases"
DEFAULT_LOCK_NAME = "tpu-operator"


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


@dataclasses.dataclass
class LeaseSpec(ApiObject):
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: Optional[_dt.datetime] = None
    renew_time: Optional[_dt.datetime] = None
    lease_transitions: int = 0


@dataclasses.dataclass
class _EmptyStatus(ApiObject):
    pass


@dataclasses.dataclass
class Lease(ApiObject):
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: LeaseSpec = dataclasses.field(default_factory=LeaseSpec)
    status: _EmptyStatus = dataclasses.field(default_factory=_EmptyStatus)


class LeaderElector:
    """Acquire-then-renew loop. ``on_started_leading`` runs (once) in a
    daemon thread after acquisition; ``on_stopped_leading`` fires if a
    renewal misses the deadline (the reference fatals there)."""

    def __init__(self, store: Store,
                 identity: Optional[str] = None,
                 namespace: str = "default",
                 name: str = DEFAULT_LOCK_NAME,
                 lease_duration: float = 15.0,
                 renew_deadline: float = 5.0,
                 retry_period: float = 3.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        if renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        self.store = store
        self.identity = identity or f"{DEFAULT_LOCK_NAME}-{uuid.uuid4().hex[:8]}"
        self.namespace = namespace
        self.name = name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._leading = threading.Event()

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    def wait_until_leading(self, timeout: Optional[float] = None) -> bool:
        return self._leading.wait(timeout)

    # -- lock record CAS -------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        try:
            return self._acquire_or_renew_once()
        except Exception:
            # ANY failure — a 5xx burst, a timeout, a dropped
            # connection — is a failed attempt, never a thread-killer:
            # before this guard, a transient error here propagated out
            # of run(), silently killing the elector thread with
            # _leading still set — a zombie leader that never renews,
            # never steps down, and blocks standby failover until the
            # humans notice (found by the injected-renew-failure tests,
            # tests/test_leaderelection.py). The caller's retry loop +
            # renew deadline turn persistent failure into a clean
            # stepdown.
            log.warning("lease acquire/renew attempt failed; retrying",
                        exc_info=True)
            return False

    def _acquire_or_renew_once(self) -> bool:
        now = _now()
        lease = self.store.try_get(LEASES, self.namespace, self.name)
        if lease is None:
            fresh = Lease(spec=LeaseSpec(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration,
                acquire_time=now, renew_time=now))
            fresh.metadata.name = self.name
            fresh.metadata.namespace = self.namespace
            try:
                self.store.create(LEASES, fresh)
                return True
            except store_mod.AlreadyExistsError:
                return False

        if lease.spec.holder_identity != self.identity:
            renew = lease.spec.renew_time
            expired = (renew is None or
                       (now - renew).total_seconds()
                       > lease.spec.lease_duration_seconds)
            if not expired:
                return False
            lease.spec.lease_transitions += 1
            lease.spec.acquire_time = now
            log.info("%s taking over expired lease from %s", self.identity,
                     lease.spec.holder_identity)

        lease.spec.holder_identity = self.identity
        lease.spec.renew_time = now
        lease.spec.lease_duration_seconds = self.lease_duration
        try:
            # Optimistic CAS: resource_version mismatch = lost the race.
            self.store.update(LEASES, lease)
            return True
        except (store_mod.ConflictError, store_mod.NotFoundError):
            return False

    def release(self) -> None:
        """Voluntarily drop the lease so a standby takes over instantly.
        Best-effort: on any failure (including transport errors during
        shutdown) the lease simply expires on its own duration."""
        try:
            lease = self.store.try_get(LEASES, self.namespace, self.name)
            if (lease is not None
                    and lease.spec.holder_identity == self.identity):
                lease.spec.holder_identity = ""
                lease.spec.renew_time = None
                self.store.update(LEASES, lease)
        except Exception:
            log.debug("lease release failed; it will expire on its own",
                      exc_info=True)

    # -- run loop --------------------------------------------------------

    def run(self) -> None:
        """Blocks until elected, then renews until stop() or lost lease."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            if self._stop.wait(self.retry_period):
                return
        if self._stop.is_set():
            return

        log.info("%s became leader", self.identity)
        self._leading.set()
        is_leader_gauge.set(1)
        if self.on_started_leading is not None:
            threading.Thread(target=self.on_started_leading,
                             name="leading", daemon=True).start()

        renew_every = min(self.renew_deadline / 2.0, 2.0)
        while not self._stop.wait(renew_every):
            deadline = _now() + _dt.timedelta(seconds=self.renew_deadline)
            renewed = False
            while _now() < deadline and not self._stop.is_set():
                if self._try_acquire_or_renew():
                    renewed = True
                    break
                self._stop.wait(min(self.retry_period, 0.5))
            if not renewed:
                log.error("%s failed to renew lease; stepping down",
                          self.identity)
                break
        self._leading.clear()
        is_leader_gauge.set(0)
        if not self._stop.is_set() and self.on_stopped_leading is not None:
            self.on_stopped_leading()

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, name="leaderelect",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._leading.clear()
        is_leader_gauge.set(0)
        # Callable from the elector's own thread (on_stopped_leading →
        # shutdown paths); a thread cannot join itself.
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        self.release()
