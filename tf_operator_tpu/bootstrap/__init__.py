"""Cluster bootstrap: slice topology -> worker ranks -> env injection.

Reference parity: pkg/controller.v1/tensorflow/tensorflow.go (TF_CONFIG
rendering) replaced by jax.distributed / libtpu-style env
(TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, coordinator address, megascale).
"""

from tf_operator_tpu.bootstrap.topology import SliceTopology, parse_accelerator  # noqa: F401
from tf_operator_tpu.bootstrap.cluster import (  # noqa: F401
    ClusterSpec,
    build_cluster_spec,
    learner_endpoints,
    render_worker_env,
)
