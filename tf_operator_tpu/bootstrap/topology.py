"""TPU slice topology model.

No reference analog — the reference was device-blind (Volcano PodGroups
carry only counts/resources). TPU-native orchestration needs the slice
shape to (a) compute process counts/ranks for jax.distributed, (b) derive
the default ICI mesh for GSPMD sharding, (c) gang-allocate whole slices.

Conventions encoded (public Cloud TPU naming):
- v2/v3/v4/v5p accelerator names count TensorCores; v5e/v6e names count
  chips (v4/v5p are "megacore": 2 cores/chip presented as one device).
- chips per host: v2/v3 -> 4, v4/v5p -> 4, v5e/v6e -> 8 (capped by slice
  size for sub-host slices).
- ICI mesh: 3D torus for v4/v5p (e.g. v5p-32 = 16 chips = 2x2x4),
  2D for v2/v3/v5e/v6e (e.g. v5e-16 = 4x4).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Tuple

_ACCEL_RE = re.compile(r"^(v[0-9]+[a-z]*)-([0-9]+)$")

# generation -> (name counts cores?, chips per host, ici mesh rank)
_GENERATIONS = {
    "v2": (True, 4, 2),
    "v3": (True, 4, 2),
    "v4": (True, 4, 3),
    "v5p": (True, 4, 3),
    "v5e": (False, 8, 2),
    "v5litepod": (False, 8, 2),
    "v6e": (False, 8, 2),
}


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    accelerator: str          # e.g. "v5p-32"
    generation: str           # e.g. "v5p"
    chips: int                # physical chips in one slice
    topology: Tuple[int, ...]  # ICI mesh, e.g. (2, 2, 4)
    chips_per_host: int
    num_slices: int = 1

    @property
    def hosts_per_slice(self) -> int:
        return max(1, self.chips // self.chips_per_host)

    @property
    def num_hosts(self) -> int:
        """Total worker processes across all slices (one per host)."""
        return self.hosts_per_slice * self.num_slices

    @property
    def devices_per_host(self) -> int:
        return min(self.chips, self.chips_per_host)

    @property
    def total_chips(self) -> int:
        return self.chips * self.num_slices

    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.topology)


def _default_topology(chips: int, rank: int) -> Tuple[int, ...]:
    """Factor ``chips`` into ``rank`` near-balanced power-of-two-ish dims,
    sorted ascending (2x2x4 rather than 4x2x2)."""
    if chips <= 0:
        raise ValueError(f"chips must be positive, got {chips}")
    dims = [1] * rank
    remaining = chips
    # Peel factors smallest-first so dims stay balanced.
    while remaining > 1:
        for factor in range(2, remaining + 1):
            if remaining % factor == 0:
                smallest = dims.index(min(dims))
                dims[smallest] *= factor
                remaining //= factor
                break
    # Cloud convention: non-trivial dims ascending, trailing 1s
    # (v4-8 -> 2x2x1, v5p-32 -> 2x2x4).
    non_trivial = sorted(d for d in dims if d > 1)
    return tuple(non_trivial + [1] * (rank - len(non_trivial)))


def parse_accelerator(accelerator: str, topology: str = "",
                      num_slices: int = 1) -> SliceTopology:
    """Parse a Cloud-TPU-style accelerator string into a SliceTopology.

    ``topology`` overrides the derived ICI mesh (e.g. "4x4" for a twisted
    v5e-16); its product must equal the chip count.
    """
    m = _ACCEL_RE.match(accelerator)
    if not m:
        raise ValueError(f"invalid accelerator {accelerator!r}; expected e.g. 'v5p-32'")
    generation, count = m.group(1), int(m.group(2))
    if generation not in _GENERATIONS:
        raise ValueError(
            f"unknown TPU generation {generation!r}; known: "
            f"{', '.join(sorted(_GENERATIONS))}")
    counts_cores, chips_per_host, rank = _GENERATIONS[generation]
    chips = count // 2 if counts_cores else count
    if chips < 1:
        raise ValueError(f"accelerator {accelerator!r} resolves to zero chips")

    if topology:
        dims = tuple(int(d) for d in topology.split("x"))
        if math.prod(dims) != chips:
            raise ValueError(
                f"topology {topology!r} has {math.prod(dims)} chips but "
                f"{accelerator!r} has {chips}")
    else:
        dims = _default_topology(chips, rank)

    if num_slices < 1:
        raise ValueError("num_slices must be >= 1")

    return SliceTopology(accelerator=accelerator, generation=generation,
                         chips=chips, topology=dims,
                         chips_per_host=chips_per_host, num_slices=num_slices)
