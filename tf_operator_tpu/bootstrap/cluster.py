"""Cluster-spec construction and worker env rendering.

This is the heart of distributed bootstrap — the TPU-native replacement
for the reference's TF_CONFIG machinery
(pkg/controller.v1/tensorflow/tensorflow.go:97-173, pod.go:259-317):

- replica DNS naming keeps the reference contract
  ``{job}-{rtype}-{index}.{ns}.svc[.{domain}]`` (tensorflow.go:154-166).
- instead of TF_CONFIG the default container receives:
  * ``TPUJOB_CLUSTER_SPEC`` — full cluster JSON (same shape as TF_CONFIG:
    cluster/task/environment) for tooling and e2e golden tests;
  * ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` — libtpu-style slice
    bootstrap;
  * ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` — jax.distributed.initialize() bootstrap; the
    coordinator is the chief (or worker-0) at a dedicated port;
  * ``TPU_ACCELERATOR_TYPE`` / ``TPU_TOPOLOGY`` — slice shape for mesh
    construction;
  * ``MEGASCALE_*`` — multislice (DCN) coordination when numSlices > 1.
- elastic mode renders a sparse cluster view (reference SparseClusterSpec,
  tensorflow.go:64-83): the worker sees itself plus parameter servers, so
  membership can change without restarting the world.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    ReplicaType,
    TPUJob,
    gen_general_name,
)
from tf_operator_tpu.bootstrap.topology import SliceTopology, parse_accelerator

# Replica-type ordering inside cluster specs and rank assignment: the
# coordinator-capable types come first so process 0 is always chief-like.
_RANKED_TYPES = (ReplicaType.CHIEF, ReplicaType.MASTER, ReplicaType.WORKER)


@dataclasses.dataclass
class ClusterSpec:
    """Full cluster view for one task (TF_CONFIG-shaped parity artifact)."""

    cluster: Dict[str, List[str]]
    task_type: str
    task_index: int
    environment: str = "cloud"

    def to_json(self) -> str:
        return json.dumps({
            "cluster": self.cluster,
            "task": {"type": self.task_type, "index": self.task_index},
            "environment": self.environment,
        }, sort_keys=True)


def replica_dns_name(job: TPUJob, rtype: str, index: int,
                     domain: str = "") -> str:
    """Reference naming contract (tensorflow.go:154-166)."""
    name = gen_general_name(job.metadata.name, rtype, index)
    host = f"{name}.{job.metadata.namespace}.svc"
    if domain:
        host = f"{host}.{domain}"
    return host


def replica_port(job: TPUJob, rtype: str) -> int:
    """Rendezvous port from the default container's named port (reference
    GetPortFromTFJob, tensorflow/util.go:28-43)."""
    spec = job.spec.replica_specs.get(rtype)
    if spec is not None:
        container = spec.template.spec.container(constants.DEFAULT_CONTAINER_NAME)
        if container is not None:
            port = container.ports.get(constants.DEFAULT_PORT_NAME)
            if port:
                return port
    return constants.DEFAULT_PORT


def is_distributed(job: TPUJob) -> bool:
    """More than one process in the cluster (reference isDistributed,
    pod.go:296-317)."""
    total = sum(s.replicas or 0 for s in job.spec.replica_specs.values())
    return total > 1


def _cluster_domain() -> str:
    return os.environ.get(constants.ENV_CUSTOM_CLUSTER_DOMAIN, "")


def build_cluster_spec(job: TPUJob, rtype: str, index: int,
                       domain: Optional[str] = None) -> ClusterSpec:
    """Build the cluster view task (rtype, index) should see.

    Dense mode lists every replica of every type (reference
    genClusterSpec, tensorflow.go:142-173). Elastic mode is sparse: the
    worker sees only itself plus all PS replicas (reference
    SparseClusterSpec, tensorflow.go:64-83); non-worker types see the
    dense view.
    """
    if domain is None:
        domain = _cluster_domain()
    rt = rtype.lower()
    sparse = (job.spec.enable_elastic_worker and rt == ReplicaType.WORKER)

    cluster: Dict[str, List[str]] = {}
    for repl_type, spec in sorted(job.spec.replica_specs.items()):
        port = replica_port(job, repl_type)
        n = spec.replicas or 0
        if sparse and repl_type not in (ReplicaType.PS, rt):
            continue
        if sparse and repl_type == rt:
            # Sparse: only this worker's own entry, keyed by its index.
            cluster[repl_type] = [
                f"{replica_dns_name(job, repl_type, index, domain)}:{port}"]
        else:
            entries = [
                f"{replica_dns_name(job, repl_type, i, domain)}:{port}"
                for i in range(n)]
            if repl_type == rt and index >= n:
                # Transient out-of-range render (elastic grow before
                # the spec settles): the view must contain THIS task —
                # it exists by construction — and nothing between n and
                # index, which does not exist yet.
                entries.append(
                    f"{replica_dns_name(job, repl_type, index, domain)}"
                    f":{port}")
            cluster[repl_type] = entries
    return ClusterSpec(cluster=cluster, task_type=rt, task_index=index)


def process_ranks(job: TPUJob) -> Dict[str, List[int]]:
    """Global jax.distributed process ids for the data-plane types
    (chief/master first, then workers). PS/evaluator replicas are not jax
    processes; they keep cluster-spec entries only."""
    ranks: Dict[str, List[int]] = {}
    next_rank = 0
    for rtype in _RANKED_TYPES:
        spec = job.spec.replica_specs.get(rtype)
        if spec is None:
            continue
        n = spec.replicas or 0
        ranks[rtype] = list(range(next_rank, next_rank + n))
        next_rank += n
    return ranks


def coordinator_address(job: TPUJob, domain: Optional[str] = None) -> str:
    """Process-0's address for jax.distributed.initialize: the chief/master
    when present, else worker-0, on the coordinator port."""
    if domain is None:
        domain = _cluster_domain()
    for rtype in _RANKED_TYPES:
        if rtype in job.spec.replica_specs:
            host = replica_dns_name(job, rtype, 0, domain)
            return f"{host}:{constants.DEFAULT_COORDINATOR_PORT}"
    raise ValueError(f"job {job.key()} has no coordinator-capable replica type")


def learner_endpoints(job: TPUJob, domain: Optional[str] = None) -> str:
    """Comma-joined 'dns:port' endpoints of the job's learner (ranked)
    replicas — what an RL actor dials to stream experience and fetch
    parameters (docs/rl.md). The ps/evaluator treatment in reverse:
    actors discover learners through env OUTSIDE every bootstrap hash,
    so neither side restarts when the other churns. Rendered from the
    current spec at pod-create time; like the sparse-elastic ps view it
    may go stale across a learner resize (actors re-resolve by DNS or
    get the fresh list on their next recreate)."""
    if domain is None:
        domain = _cluster_domain()
    endpoints: List[str] = []
    for rtype in _RANKED_TYPES:
        spec = job.spec.replica_specs.get(rtype)
        if spec is None:
            continue
        port = replica_port(job, rtype)
        for i in range(spec.replicas or 0):
            endpoints.append(
                f"{replica_dns_name(job, rtype, i, domain)}:{port}")
    return ",".join(endpoints)


def render_worker_env(job: TPUJob, rtype: str, index: int,
                      domain: Optional[str] = None) -> Dict[str, str]:
    """Env the engine injects into the default container at pod-create time
    (the SetClusterSpec plugin hook)."""
    if domain is None:
        domain = _cluster_domain()
    rt = rtype.lower()
    env: Dict[str, str] = {}

    sl = job.spec.slice
    topo: Optional[SliceTopology] = None
    if sl.accelerator:
        topo = parse_accelerator(sl.accelerator, sl.topology, sl.num_slices)
        env["TPU_ACCELERATOR_TYPE"] = topo.accelerator
        env["TPU_TOPOLOGY"] = topo.topology_str

    if not is_distributed(job):
        return env

    env["TPUJOB_CLUSTER_SPEC"] = build_cluster_spec(job, rt, index, domain).to_json()

    ranks = process_ranks(job)
    num_processes = sum(len(v) for v in ranks.values())
    if rt in ranks and num_processes > 0:
        if index < len(ranks[rt]):
            rank = ranks[rt][index]
        else:
            # Transient out-of-range render (elastic scale-up before the
            # spec settles): offset by the type's base rank and widen the
            # process count so the id is unique and in range.
            base = ranks[rt][0] if ranks[rt] else num_processes
            rank = base + index
            num_processes = max(num_processes, rank + 1)
        env["JAX_COORDINATOR_ADDRESS"] = coordinator_address(job, domain)
        env["JAX_NUM_PROCESSES"] = str(num_processes)
        env["JAX_PROCESS_ID"] = str(rank)

        if topo is None:
            # Plain process job (no TPU slice declared): legacy behavior,
            # every ranked process is a "worker host".
            env["TPU_WORKER_ID"] = str(rank)
            hostnames = []
            for t in _RANKED_TYPES:
                spec = job.spec.replica_specs.get(t)
                for i in range(spec.replicas or 0) if spec else ():
                    hostnames.append(replica_dns_name(job, t, i, domain))
            env["TPU_WORKER_HOSTNAMES"] = ",".join(hostnames)
        elif rt == ReplicaType.WORKER:
            # TPU slice hosts are the *workers*, assigned slice-major by
            # worker index. Semantics (round-2 hardening, all slice
            # counts):
            #  - JAX_* stay GLOBAL: jax.distributed rendezvous spans all
            #    processes (coordinator included) across slices;
            #  - TPU_WORKER_ID / TPU_WORKER_HOSTNAMES are PER-SLICE:
            #    libtpu scopes slice bring-up to the slice, so the id is
            #    index % hosts_per_slice and the hostnames list only this
            #    slice's workers (a chief/master offsets the global rank
            #    but must never appear in the TPU host list);
            #  - multislice additionally gets MEGASCALE_* incl. a
            #    per-slice coordinator (the slice's first worker).
            hps = max(1, topo.hosts_per_slice)
            slice_id = index // hps
            n_workers = (job.spec.replica_specs[rt].replicas or 0)
            lo = slice_id * hps
            hi = min(lo + hps, max(n_workers, index + 1))
            # Clamp to pods that exist: on a transient out-of-range
            # render (elastic grow before the spec settles) the slice
            # window would otherwise name workers between n_workers and
            # index that have not been created yet — a worker handed
            # such a view dials hosts that do not resolve. The pod's
            # OWN name always belongs (it is the pod being rendered).
            slice_hosts = [replica_dns_name(job, rt, i, domain)
                           for i in range(lo, hi)
                           if i < n_workers or i == index]
            env["TPU_WORKER_ID"] = str(index % hps)
            env["TPU_WORKER_HOSTNAMES"] = ",".join(slice_hosts)
            if topo.num_slices > 1:
                env["MEGASCALE_COORDINATOR_ADDRESS"] = \
                    env["JAX_COORDINATOR_ADDRESS"]
                env["MEGASCALE_NUM_SLICES"] = str(topo.num_slices)
                env["MEGASCALE_SLICE_ID"] = str(slice_id)
                env["MEGASCALE_SLICE_COORDINATOR"] = (
                    f"{slice_hosts[0]}:{replica_port(job, rt)}")
        else:
            # chief/master/evaluator on a TPU job: a coordinator-only
            # process, not a slice host — global JAX_* env, no TPU slice
            # membership claims.
            if topo.num_slices > 1:
                env["MEGASCALE_COORDINATOR_ADDRESS"] = \
                    env["JAX_COORDINATOR_ADDRESS"]
                env["MEGASCALE_NUM_SLICES"] = str(topo.num_slices)

    return env
